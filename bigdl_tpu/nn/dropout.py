"""Dropout and gradient-trick layers.

Reference: BigDL `nn/Dropout.scala` (inverted-scaling dropout over a bernoulli
mask), `nn/GradientReversal.scala`.

TPU-native notes: the bernoulli mask comes from the explicit PRNG key threaded
through `apply` — deterministic under jit and independent of device count.

`LookupTable` moved to nn/embedding.py (PR 20); the re-export below keeps
`bigdl_tpu.nn.dropout.LookupTable` imports and bigdl-format save/load (keyed
by class name) working unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module
from .embedding import LookupTable  # noqa: F401  (re-export, see docstring)

__all__ = ["Dropout", "LookupTable", "GradientReversal"]


class Dropout(Module):
    """Inverted dropout (nn/Dropout.scala): zero with prob p, scale by 1/(1-p)
    when `scale` (the reference's default) is true."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale

    def set_p(self, p: float):
        self.p = p
        return self

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout in training mode requires an rng key")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        y = jnp.where(mask, x, 0.0)
        if self.scale:
            y = y / keep
        return y.astype(x.dtype), state


class GradientReversal(Module):
    """Identity forward, -lambda * grad backward (nn/GradientReversal.scala) —
    via jax.custom_vjp so it also works inside the compiled train step."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self.the_lambda = the_lambda

        @jax.custom_vjp
        def rev(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            return (jax.tree.map(lambda t: -self.the_lambda * t, g),)

        rev.defvjp(fwd, bwd)
        self._rev = rev

    def set_lambda(self, lam: float):
        self.the_lambda = lam
        return self

    def _apply(self, params, x):
        return self._rev(x)
