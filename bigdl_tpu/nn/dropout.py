"""Dropout and embedding layers.

Reference: BigDL `nn/Dropout.scala` (inverted-scaling dropout over a bernoulli
mask), `nn/LookupTable.scala` (embedding with optional max-norm renorm),
`nn/GradientReversal.scala`.

TPU-native notes: the bernoulli mask comes from the explicit PRNG key threaded
through `apply` — deterministic under jit and independent of device count.
LookupTable is a gather (one-hot matmul is left to XLA's discretion).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common import get_policy
from .module import Module

__all__ = ["Dropout", "LookupTable", "GradientReversal"]


class Dropout(Module):
    """Inverted dropout (nn/Dropout.scala): zero with prob p, scale by 1/(1-p)
    when `scale` (the reference's default) is true."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale

    def set_p(self, p: float):
        self.p = p
        return self

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout in training mode requires an rng key")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        y = jnp.where(mask, x, 0.0)
        if self.scale:
            y = y / keep
        return y.astype(x.dtype), state


class LookupTable(Module):
    """Embedding lookup (nn/LookupTable.scala): indices -> rows of a
    (n_index, n_output) weight.  Indices are 0-based (reference is 1-based Torch;
    pass `one_based=True` for parity with reference data)."""

    #: rows shard over fsdp x tp (the wide-embedding role, SNIPPETS.md [2])
    PARAM_ROLES = {"weight": "embedding_row"}

    def __init__(self, n_index: int, n_output: int, padding_value: float = None,
                 max_norm: float = None, norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False, one_based: bool = False,
                 w_regularizer=None):
        super().__init__()
        self.n_index, self.n_output = n_index, n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.one_based = one_based
        self.w_regularizer = w_regularizer

    def _init(self, rng):
        w = jax.random.normal(rng, (self.n_index, self.n_output),
                              get_policy().param_dtype)
        if self.padding_value is not None:
            pad_idx = int(self.padding_value) - (1 if self.one_based else 0)
            if 0 <= pad_idx < self.n_index:
                w = w.at[pad_idx].set(0.0)
        return {"weight": w}

    def _apply(self, params, idx):
        w = params["weight"]
        if self.max_norm is not None:
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1, keepdims=True)
            w = jnp.where(norms > self.max_norm, w * (self.max_norm / norms), w)
        i = idx.astype(jnp.int32)
        if self.one_based:
            i = i - 1
        return jnp.take(w, i, axis=0)


class GradientReversal(Module):
    """Identity forward, -lambda * grad backward (nn/GradientReversal.scala) —
    via jax.custom_vjp so it also works inside the compiled train step."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self.the_lambda = the_lambda

        @jax.custom_vjp
        def rev(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            return (jax.tree.map(lambda t: -self.the_lambda * t, g),)

        rev.defvjp(fwd, bwd)
        self._rev = rev

    def set_lambda(self, lam: float):
        self.the_lambda = lam
        return self

    def _apply(self, params, x):
        return self._rev(x)
