"""Recurrent layers.

Reference: BigDL `nn/Recurrent.scala:33` unrolls a `Cell` over the time dimension
with a Scala while-loop over cloned-and-weight-shared cells (:80-152) — a
sequential, per-timestep, per-process loop.  Cells: `nn/Cell.scala:44` (base),
`nn/RNN.scala` (RnnCell), `nn/LSTM.scala`, `nn/LSTMPeephole.scala`, `nn/GRU.scala`,
`nn/ConvLSTMPeephole.scala`; wrappers `nn/TimeDistributed.scala`,
`nn/BiRecurrent.scala`.

TPU-native re-design: the time loop is `jax.lax.scan` — ONE compiled loop with the
cell's gate matmuls fused into a single (in+hidden, 4*hidden) MXU-friendly gemm per
step; weights are trivially shared because the same params pytree is closed over
every step.  Layout: (batch, time, features), scanned time-major internally.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..common import conv_accum_dtype, get_policy
from ..utils import config as _config
from .module import Container, Module

__all__ = ["Cell", "RnnCell", "LSTM", "LSTMPeephole", "GRU", "ConvLSTMPeephole",
           "Recurrent", "TimeDistributed", "BiRecurrent"]


class Cell(Module):
    """RNN-cell base (reference: nn/Cell.scala:44).

    Contract: `init_hidden(batch_size, dtype)` -> hidden pytree;
    `step(params, x_t, hidden)` -> (output_t, new_hidden), both pure.
    """

    hidden_size: int

    def init_hidden(self, batch_size: int, dtype=jnp.float32):
        raise NotImplementedError

    def step(self, params, x_t, hidden):
        """One timestep.  Cells that implement project_inputs/step_projected
        (all built-ins, dense and conv) inherit this delegation (a (1,B,...)
        projection), so the single-step path and Recurrent's hoisted scan
        share ONE set of equations; custom cells may override step()
        directly and Recurrent falls back to scanning it."""
        proj = self.project_inputs(params, x_t[None])
        if proj is None:
            raise NotImplementedError
        xp_t = jax.tree.map(lambda p: p[0], proj)
        return self.step_projected(params, xp_t, hidden)

    # -- input-projection hoisting (TPU optimization) ----------------------
    # The x-half of every gate projection is state-independent, so it can
    # leave the scan: ONE (T*B, I) @ (I, G) MXU gemm up front instead of T
    # small gemms interleaved with the sequential dependency.  Exact same
    # math (blocked matmul: [x,h] @ K == x@Kx + h@Kh).  Cells implementing
    # the pair are hoisted automatically by Recurrent; a cell may return
    # None from project_inputs (custom step()-only cells always do, conv
    # cells do above a size threshold) to take the plain-step scan branch.

    def project_inputs(self, params, xs):
        """xs time-major (T, B, I) -> pytree scanned in place of xs, or None
        when the cell doesn't support hoisting."""
        return None

    def step_projected(self, params, xp_t, hidden):
        raise NotImplementedError

    # a bare cell applied to (batch, features) input acts on one step with zero state
    def _apply(self, params, x):
        out, _ = self.step(params, x, self.init_hidden(x.shape[0], x.dtype))
        return out


def _uniform(rng, shape, stdv):
    return jax.random.uniform(rng, shape, get_policy().param_dtype, -stdv, stdv)


def _dense_hoist_ok(xs, gate_width):
    """HBM guard for the dense cells' input-projection hoisting: the hoisted
    (T, B, gate_width) f32 projection lives for the whole scan and can OOM
    where the un-hoisted per-step scan fit (long sequence x large hidden).
    Same cap and t == 1 exemption as ConvLSTM's project_inputs — one step's
    projection is the gates tensor the per-step path materializes anyway."""
    t, b = xs.shape[0], xs.shape[1]
    return t == 1 or t * b * gate_width <= _config.get_int(
        "RNN_HOIST_MAX_ELEMENTS", 1 << 28)


def _project(xs, w):
    """(T, B, I) @ (I, G) as one flat MXU gemm, f32 accumulation."""
    cd = get_policy().compute_dtype
    t, b, i = xs.shape
    flat = xs.reshape(t * b, i).astype(cd)
    proj = lax.dot_general(flat, w.astype(cd), (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    return proj.reshape(t, b, -1)


class RnnCell(Cell):

    PARAM_ROLES = {"w_ih": "kernel_in", "w_hh": "kernel_in",
                   "bias": "bias"}
    """Vanilla RNN: h' = act(W x + U h + b) (reference: nn/RNN.scala RnnCell)."""

    def __init__(self, input_size: int, hidden_size: int, activation=jnp.tanh):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation

    def _init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        stdv = 1.0 / (self.hidden_size ** 0.5)
        return {"w_ih": _uniform(k1, (self.input_size, self.hidden_size), stdv),
                "w_hh": _uniform(k2, (self.hidden_size, self.hidden_size), stdv),
                "bias": _uniform(k3, (self.hidden_size,), stdv)}

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def project_inputs(self, params, xs):
        if not _dense_hoist_ok(xs, self.hidden_size):
            return None
        return _project(xs, params["w_ih"])

    def step_projected(self, params, xp_t, h):
        c = get_policy().compute_dtype
        pre = xp_t + h.astype(c) @ params["w_hh"].astype(c) + params["bias"]
        h_new = self.activation(pre).astype(h.dtype)
        return h_new, h_new


class LSTM(Cell):

    PARAM_ROLES = {"kernel": "kernel_in", "bias": "bias"}
    """LSTM cell (reference: nn/LSTM.scala).  The four gate projections are
    fused into one (in+hidden, 4*hidden) kernel; under Recurrent's scan the
    x-half is hoisted out as one big (T*B, in) gemm and each step runs only
    the state-dependent (B, hidden) @ (hidden, 4*hidden) gemm.
    Gate order: input, forget, cell(gain), output."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p  # dropout on gate inputs (reference's p) — applied by Recurrent

    def _init(self, rng):
        k1, k2 = jax.random.split(rng)
        stdv = 1.0 / (self.hidden_size ** 0.5)
        return {
            "kernel": _uniform(k1, (self.input_size + self.hidden_size,
                                    4 * self.hidden_size), stdv),
            "bias": _uniform(k2, (4 * self.hidden_size,), stdv),
        }

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return (jnp.zeros((batch_size, self.hidden_size), dtype),
                jnp.zeros((batch_size, self.hidden_size), dtype))

    def step_projected(self, params, xp_t, hidden):
        h, cst = hidden
        cd = get_policy().compute_dtype
        gates = xp_t + lax.dot_general(
            h.astype(cd), params["kernel"][self.input_size:].astype(cd),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        gates = gates + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * cst.astype(jnp.float32) + i * g
        h_new = o * jnp.tanh(c_new)
        h_new = h_new.astype(h.dtype)
        return h_new, (h_new, c_new.astype(h.dtype))

    def project_inputs(self, params, xs):
        if not _dense_hoist_ok(xs, 4 * self.hidden_size):
            return None
        return _project(xs, params["kernel"][: self.input_size])


class LSTMPeephole(Cell):

    PARAM_ROLES = {"kernel": "kernel_in", "bias": "bias",
                   "peep_i": "elementwise", "peep_f": "elementwise",
                   "peep_o": "elementwise"}
    """LSTM with peephole connections (reference: nn/LSTMPeephole.scala):
    gates also see the cell state through diagonal weights."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p

    def _init(self, rng):
        ks = jax.random.split(rng, 5)
        stdv = 1.0 / (self.hidden_size ** 0.5)
        H = self.hidden_size
        return {
            "kernel": _uniform(ks[0], (self.input_size + H, 4 * H), stdv),
            "bias": _uniform(ks[1], (4 * H,), stdv),
            "peep_i": _uniform(ks[2], (H,), stdv),
            "peep_f": _uniform(ks[3], (H,), stdv),
            "peep_o": _uniform(ks[4], (H,), stdv),
        }

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return (jnp.zeros((batch_size, self.hidden_size), dtype),
                jnp.zeros((batch_size, self.hidden_size), dtype))

    def step_projected(self, params, xp_t, hidden):
        h, cst = hidden
        cd = get_policy().compute_dtype
        gates = xp_t + lax.dot_general(
            h.astype(cd), params["kernel"][self.input_size:].astype(cd),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        gates = gates + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        cf = cst.astype(jnp.float32)
        i = jax.nn.sigmoid(i + params["peep_i"] * cf)
        f = jax.nn.sigmoid(f + params["peep_f"] * cf)
        g = jnp.tanh(g)
        c_new = f * cf + i * g
        o = jax.nn.sigmoid(o + params["peep_o"] * c_new)
        h_new = (o * jnp.tanh(c_new)).astype(h.dtype)
        return h_new, (h_new, c_new.astype(h.dtype))

    def project_inputs(self, params, xs):
        if not _dense_hoist_ok(xs, 4 * self.hidden_size):
            return None
        return _project(xs, params["kernel"][: self.input_size])


class GRU(Cell):

    PARAM_ROLES = {"gate_kernel": "kernel_in", "gate_bias": "bias",
                   "cand_kernel": "kernel_in", "cand_bias": "bias"}
    """GRU cell (reference: nn/GRU.scala). Reset/update gates fused in one gemm."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p

    def _init(self, rng):
        ks = jax.random.split(rng, 4)
        stdv = 1.0 / (self.hidden_size ** 0.5)
        H = self.hidden_size
        return {
            "gate_kernel": _uniform(ks[0], (self.input_size + H, 2 * H), stdv),
            "gate_bias": _uniform(ks[1], (2 * H,), stdv),
            "cand_kernel": _uniform(ks[2], (self.input_size + H, H), stdv),
            "cand_bias": _uniform(ks[3], (H,), stdv),
        }

    def init_hidden(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def step_projected(self, params, xp_t, h):
        cd = get_policy().compute_dtype
        I = self.input_size
        xp_gate, xp_cand = xp_t
        gates = jax.nn.sigmoid(
            xp_gate + lax.dot_general(
                h.astype(cd), params["gate_kernel"][I:].astype(cd),
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
            + params["gate_bias"])
        r, u = jnp.split(gates, 2, axis=-1)
        rh = (r * h.astype(jnp.float32)).astype(cd)
        cand = jnp.tanh(
            xp_cand + lax.dot_general(
                rh, params["cand_kernel"][I:].astype(cd),
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
            + params["cand_bias"])
        h_new = ((1.0 - u) * h.astype(jnp.float32) + u * cand).astype(h.dtype)
        return h_new, h_new

    def project_inputs(self, params, xs):
        if not _dense_hoist_ok(xs, 3 * self.hidden_size):  # both trees
            return None
        I = self.input_size
        return (_project(xs, params["gate_kernel"][:I]),
                _project(xs, params["cand_kernel"][:I]))


class ConvLSTMPeephole(Cell):

    PARAM_ROLES = {"kernel": "conv_kernel", "bias": "bias",
                   "peep_i": "elementwise", "peep_f": "elementwise",
                   "peep_o": "elementwise"}
    """Convolutional LSTM with peepholes over NHWC maps
    (reference: nn/ConvLSTMPeephole.scala)."""

    #: spatial rank; ConvLSTMPeephole3D overrides with 3 (NDHWC maps)
    SPATIAL_NDIM = 2
    _DIM_NUMBERS = {2: ("NHWC", "HWIO", "NHWC"),
                    3: ("NDHWC", "DHWIO", "NDHWC")}

    def __init__(self, input_size: int, output_size: int, kernel_i: int = 3,
                 kernel_c: int = 3, stride: int = 1, with_peephole: bool = True):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.kernel = kernel_i
        self.stride = stride
        self.with_peephole = with_peephole
        self.hidden_size = output_size
        self._spatial = None  # spatial dims tuple, bound at first step

    def _init(self, rng):
        ks = jax.random.split(rng, 5)
        k, n = self.kernel, self.SPATIAL_NDIM
        cin = self.input_size + self.output_size
        fan_in = (k ** n) * cin
        stdv = 1.0 / (fan_in ** 0.5)
        p = {"kernel": _uniform(ks[0], (k,) * n + (cin, 4 * self.output_size),
                                stdv),
             "bias": _uniform(ks[1], (4 * self.output_size,), stdv)}
        if self.with_peephole:
            p["peep_i"] = jnp.zeros((self.output_size,), jnp.float32)
            p["peep_f"] = jnp.zeros((self.output_size,), jnp.float32)
            p["peep_o"] = jnp.zeros((self.output_size,), jnp.float32)
        return p

    def init_hidden(self, batch_size, dtype=jnp.float32, spatial=None):
        if spatial is None:
            spatial = self._spatial
        z = jnp.zeros((batch_size,) + tuple(spatial) + (self.output_size,),
                      dtype)
        return (z, z)

    def _gate_conv(self, x, kernel):
        n = self.SPATIAL_NDIM
        pad = self.kernel // 2
        return lax.conv_general_dilated(
            x, kernel.astype(x.dtype), (self.stride,) * n, [(pad, pad)] * n,
            dimension_numbers=self._DIM_NUMBERS[n],
            preferred_element_type=conv_accum_dtype())

    def project_inputs(self, params, xs):
        # conv is linear in input channels, so conv([x,h], K) splits exactly
        # into conv(x, Kx) + conv(h, Kh); fold T into batch for ONE conv.
        # Hoisting materializes (T, B, *spatial, 4*output) gate projections
        # in HBM for the whole scan (~4x the scan's own stacked output) —
        # above BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS, fall back to the per-step
        # conv instead of risking an OOM the un-hoisted code never had.
        # t == 1 (the Cell.step delegation) is exempt: a one-step projection
        # is the very gates tensor the fused per-step conv materializes too,
        # so there is no fallback with a smaller working set.
        import math as _math
        t, b = xs.shape[0], xs.shape[1]
        proj_elems = (t * b * 4 * self.output_size *
                      _math.prod(xs.shape[2:2 + self.SPATIAL_NDIM]))
        if t > 1 and proj_elems > _config.get_int("RNN_HOIST_MAX_ELEMENTS",
                                                  1 << 28):
            return None
        flat = xs.reshape((t * b,) + xs.shape[2:])
        proj = self._gate_conv(flat, params["kernel"][..., : self.input_size, :])
        return proj.reshape((t, b) + proj.shape[1:])

    def step_projected(self, params, xp_t, hidden):
        h, cst = hidden
        gates = xp_t + self._gate_conv(
            h, params["kernel"][..., self.input_size:, :]) + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        cf = cst.astype(jnp.float32)
        if self.with_peephole:
            i = i + params["peep_i"] * cf
            f = f + params["peep_f"] * cf
        i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c_new = f * cf + i * g
        if self.with_peephole:
            o = o + params["peep_o"] * c_new
        o = jax.nn.sigmoid(o)
        h_new = (o * jnp.tanh(c_new)).astype(h.dtype)
        return h_new, (h_new, c_new.astype(h.dtype))


class ConvLSTMPeephole3D(ConvLSTMPeephole):
    """Volumetric ConvLSTM with peepholes over NDHWC maps
    (reference: nn/ConvLSTMPeephole3D.scala); input
    (batch, time, D, H, W, C) under Recurrent."""

    SPATIAL_NDIM = 3


class Recurrent(Container):
    """Unroll a Cell over the time axis of (batch, time, features...) input
    (reference: nn/Recurrent.scala:33; the clone-per-timestep loop becomes
    ONE lax.scan)."""

    def __init__(self, cell: Cell = None):
        super().__init__()
        if cell is not None:
            self.add(cell)
        self._return_state = False

    def apply(self, params, state, x, *, training=False, rng=None):
        cell: Cell = self.modules[0]
        cp = params[0]
        if isinstance(cell, ConvLSTMPeephole):
            cell._spatial = tuple(x.shape[2:2 + cell.SPATIAL_NDIM])
        # cell input dropout (the reference's `p` on LSTM/GRU,
        # nn/LSTM.scala) — applied as VARIATIONAL dropout: one mask shared
        # across all time steps (a TPU-friendly re-design; the reference draws
        # per-gate masks per step)
        p = getattr(cell, "p", 0.0)
        if training and p > 0.0 and rng is not None:
            keep = 1.0 - p
            mask = jax.random.bernoulli(rng, keep, (x.shape[0],) + x.shape[2:])
            x = jnp.where(mask[:, None], x, 0.0) / keep
        h0 = cell.init_hidden(x.shape[0], x.dtype)
        xs = jnp.moveaxis(x, 1, 0)  # time-major for scan

        proj = cell.project_inputs(cp, xs)
        if proj is not None:
            # input half of the gate projections hoisted to one big gemm
            # (after dropout, so masks still apply); the scan body carries
            # only the state-dependent hidden gemm
            def body(h, xp_t):
                out, h_new = cell.step_projected(cp, xp_t, h)
                return h_new, out

            h_last, outs = lax.scan(body, h0, proj)
        else:
            def body(h, x_t):
                out, h_new = cell.step(cp, x_t, h)
                return h_new, out

            h_last, outs = lax.scan(body, h0, xs)
        out = jnp.moveaxis(outs, 0, 1)  # back to (batch, time, ...)
        if self._return_state:
            return (out, h_last), state
        return out, state


class TimeDistributed(Container):
    """Apply a layer independently at every time step (reference:
    nn/TimeDistributed.scala) — a reshape, not a loop: (b, t, ...) -> (b*t, ...)."""

    def __init__(self, module: Module):
        super().__init__(module)

    def apply(self, params, state, x, *, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        out, ns = self.modules[0].apply(params[0], state[0], flat,
                                        training=training, rng=rng)
        return out.reshape((b, t) + out.shape[1:]), [ns]


class BiRecurrent(Container):
    """Bidirectional wrapper (reference: nn/BiRecurrent.scala): run the cell
    forward and (a separate copy) backward over time, merge with `merge`
    ('concat' along features, or 'sum' — reference default is CAddTable/sum)."""

    def __init__(self, cell: Cell, merge: str = "sum"):
        super().__init__()
        import copy
        self.add(Recurrent(cell))
        self.add(Recurrent(copy.deepcopy(cell)))
        self.merge = merge

    def apply(self, params, state, x, *, training=False, rng=None):
        fwd, ns0 = self.modules[0].apply(params[0], state[0], x,
                                         training=training, rng=rng)
        rev_in = jnp.flip(x, axis=1)
        bwd, ns1 = self.modules[1].apply(params[1], state[1], rev_in,
                                         training=training, rng=rng)
        bwd = jnp.flip(bwd, axis=1)
        if self.merge == "concat":
            out = jnp.concatenate([fwd, bwd], axis=-1)
        else:
            out = fwd + bwd
        return out, [ns0, ns1]
