"""Normalization layers.

Reference: BigDL `nn/BatchNormalization.scala` (747 LoC of hand-rolled mean/var
loops + running-stat EMA), `nn/SpatialBatchNormalization.scala`,
`nn/SpatialCrossMapLRN.scala`, `nn/SpatialWithinChannelLRN.scala`,
`nn/Normalize.scala`, `nn/SpatialDivisiveNormalization.scala`,
`nn/SpatialSubtractiveNormalization.scala`, `nn/SpatialContrastiveNormalization.scala`.

TPU-native notes: batch-norm is a fused reduce+scale XLA graph; running statistics
live in the module's `state` pytree (the functional analog of the reference's
mutable runningMean/runningVar tensors), updated only when training=True.  Under
the default jit/GSPMD data-parallel path the reductions run over the GLOBAL
logical batch — XLA inserts a (cheap, per-channel-vector) cross-device
all-reduce — i.e. sync-BN semantics out of the box.  This differs from the
reference, where each model replica normalizes over only its local sub-batch
(DistriOptimizer.scala:165-183); global stats are the statistically stronger
behavior and the natural GSPMD lowering, so it is the default here.  The
explicit `sync_axis=` + `lax.pmean` path exists for `shard_map` contexts
(bigdl_tpu.parallel), where reductions really are per-shard unless synced.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..common import get_policy
from ..utils import config
from .module import Module

__all__ = ["BatchNormalization", "SpatialBatchNormalization", "Normalize",
           "SpatialCrossMapLRN", "SpatialWithinChannelLRN",
           "SpatialSubtractiveNormalization", "SpatialDivisiveNormalization",
           "SpatialContrastiveNormalization"]


def _bn_train_fwd(eps, x, weight, bias):
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    meansq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
    var = meansq - jnp.square(mean)
    inv = lax.rsqrt(var + eps)
    scale = weight * inv
    shift = bias - mean * scale
    y = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return y, (mean, var)


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_bn_train(eps, x, weight, bias):
    """Training-mode BN with a hand-written backward.

    The autodiff backward through the explicit stat graph and this canonical
    closed form (dx = scale * (dy - mean(dy) - xhat * mean(dy*xhat))) compute
    the same values; the hand-written version pins the pass structure to one
    fused (x, dy) reduction pass plus one dx pass and saves only per-channel
    vectors (mean, inv) — x is the layer's input and already live.  Measured
    on the v5e chip via bigdl_tpu.tools.bn_experiment; enabled by
    BIGDL_TPU_BN_FUSED_VJP (see BatchNormalization).
    """
    y, _ = _bn_train_fwd(eps, x, weight, bias)
    return y


def _fused_bn_fwd_res(eps, x, weight, bias):
    y, (mean, var) = _bn_train_fwd(eps, x, weight, bias)
    inv = lax.rsqrt(var + eps)
    return y, (x, mean, inv, weight)


def _fused_bn_bwd(eps, res, dy):
    x, mean, inv, weight = res
    axes = tuple(range(x.ndim - 1))
    n = 1
    for ax in axes:
        n *= x.shape[ax]
    xhat = (x.astype(jnp.float32) - mean) * inv
    dyf = dy.astype(jnp.float32)
    sum_dy = jnp.sum(dyf, axis=axes)
    sum_dy_xhat = jnp.sum(dyf * xhat, axis=axes)
    scale = (weight * inv).astype(x.dtype)
    dx = scale * (dy
                  - (sum_dy / n).astype(x.dtype)
                  - xhat.astype(x.dtype) * (sum_dy_xhat / n).astype(x.dtype))
    return dx, sum_dy_xhat.astype(weight.dtype), sum_dy.astype(weight.dtype)


_fused_bn_train.defvjp(_fused_bn_fwd_res, _fused_bn_bwd)


class BatchNormalization(Module):

    PARAM_ROLES = {"weight": "norm_scale", "bias": "norm_scale"}
    """BN over the last (feature) axis; all leading axes are reduction axes.

    Reference: nn/BatchNormalization.scala (eps/momentum/affine semantics,
    runningMean/runningVar EMA: new = (1-momentum)*old + momentum*batch).

    Training-mode stat machinery is the measured MFU bottleneck on TPU
    (docs/benchmarking.md), so the implementation is selectable via the
    config tier (SURVEY §5.6) for `bigdl_tpu.tools.bn_experiment` to race:

    - BIGDL_TPU_BN_FUSED_VJP=1 — `_fused_bn_train`'s hand-written backward
      instead of autodiff; identical numerics, different pass structure.
    - BIGDL_TPU_BN_IMPL=pallas — the hand-scheduled Pallas kernels
      (ops/batchnorm: 2 reads + 1 write per direction, stats resident in
      VMEM).  Single device uses the fused two-phase kernel (`bn_train`);
      on a mesh the layer wraps the per-shard stat kernels in `shard_map`
      over the Engine data axis with psum'd per-channel stats
      (`bn_train_sync`) — identical sync-BN semantics to the GSPMD
      default.  `pallas_interpret` runs the kernels in interpret mode
      (CPU tests); any non-TPU backend interprets automatically.
    - BIGDL_TPU_BN_STAT_ROWS=k — ghost-batch statistics: mean/var from the
      first k rows of the batch only (shuffled batches make this a random
      subsample), cutting the stat pass's HBM reads by N/k.  Normalization
      and gradients still cover every row; stats are a biased-to-the-subset
      estimate, the same trade ghost batch norm makes deliberately.
    """

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, sync_axis: str = None):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.sync_axis = sync_axis  # mesh axis name for cross-replica sync-BN

    def _init(self, rng):
        if not self.affine:
            return {}
        dt = get_policy().param_dtype
        winit = self.weight_initializer
        w = (winit(rng, (self.n_output,), self.n_output, self.n_output, dt)
             if winit else jnp.ones((self.n_output,), dt))
        return {"weight": w, "bias": jnp.zeros((self.n_output,), dt)}

    def _init_state(self):
        dt = get_policy().param_dtype
        return {"running_mean": jnp.zeros((self.n_output,), dt),
                "running_var": jnp.ones((self.n_output,), dt)}

    def apply(self, params, state, x, *, training=False, rng=None):
        axes = tuple(range(x.ndim - 1))
        if training:
            impl = config.get_str("BN_IMPL", "")
            if impl.startswith("pallas") and self.affine:
                # GSPMD cannot partition the opaque pallas_call, so the
                # multi-device routes split the kernel at the cross-chip
                # reduction: per-shard Pallas stat kernels + psum of the
                # per-channel vectors (ops/batchnorm.bn_train_sync) —
                # identical sync-BN semantics to the default GSPMD path.
                out = self._route_pallas(params, state, x, axes, impl)
                if out is not None:
                    return out
            stat_rows = config.get_int("BN_STAT_ROWS", 0)
            xs = x[:stat_rows] if 0 < stat_rows < x.shape[0] else x
            xf = xs.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
            if (self.affine and self.sync_axis is None
                    and config.get_bool("BN_FUSED_VJP") and xs is x):
                return self._apply_fused(params, state, x, mean, var, axes)
            if self.sync_axis is not None:
                mean = lax.pmean(mean, self.sync_axis)
                var = lax.pmean(var, self.sync_axis)
            n = 1
            for ax in axes:
                n *= xs.shape[ax]
            if self.sync_axis is not None:
                n = n * lax.psum(1, self.sync_axis)  # global element count
            new_state = self._ema_update(state, mean, var, n)
        else:
            mean = state["running_mean"]
            var = state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        if self.affine:
            scale = params["weight"] * inv
            shift = params["bias"] - mean * scale
        else:
            scale = inv
            shift = -mean * inv
        y = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return y, new_state

    def _ema_update(self, state, mean, var, n):
        """Torch-lineage convention (reference BatchNormalization.scala,
        torch BN): normalize with the BIASED batch var, but accumulate the
        UNBIASED one into the running EMA.  `n` is the element count the
        stats were computed over (per-shard or global)."""
        m = self.momentum
        unbiased = var * (n / jnp.maximum(n - 1, 1))
        dt = state["running_mean"].dtype
        return {
            "running_mean": (1 - m) * state["running_mean"]
            + m * lax.stop_gradient(mean).astype(dt),
            "running_var": (1 - m) * state["running_var"]
            + m * lax.stop_gradient(unbiased).astype(dt),
        }

    def _route_pallas(self, params, state, x, axes, impl):
        """Pick the Pallas BN route; None = no route applies (caller falls
        through to the jnp paths)."""
        from ..utils.platform import backend_kind
        backend = backend_kind()  # resolves TPU plugin names like 'axon'
        # interpret mode: explicit request (tests) or the CPU backend (the
        # CPU-mesh dryrun/conftest runs the same kernels simulated).  Other
        # non-TPU backends (GPU) get the jnp path instead — silently
        # simulating the kernels there would pessimize training under a
        # flag whose whole point is performance.
        if backend not in ("tpu", "cpu") and impl != "pallas_interpret":
            return None
        interpret = impl == "pallas_interpret" or backend == "cpu"
        if self.sync_axis is not None:
            # already inside a shard_map body (bigdl_tpu.parallel): reduce
            # over the caller's axis with psum directly
            return self._apply_pallas_sync(params, state, x,
                                           self.sync_axis, interpret)
        # mesh route FIRST (matching ConvBN.apply): under an explicit
        # pallas_interpret opt-in on a multi-device data mesh, the layer
        # must still wrap the kernel in shard_map — the single-device
        # pallas_call is opaque to GSPMD and would be all-gathered onto
        # every chip inside a multi-device jit
        if jax.device_count() > 1:
            from ..utils.engine import Engine
            mesh = Engine._mesh
            if self.shardmap_route_engages(mesh, x.shape[0]):
                return self._apply_pallas_shardmap(params, state, x, mesh,
                                                   interpret)
        if impl == "pallas_interpret" or jax.device_count() == 1:
            return self._apply_pallas(params, state, x, axes, interpret)
        return None

    @staticmethod
    def shardmap_route_engages(mesh, batch_rows: int) -> bool:
        """True when the kernel-in-shard_map route applies: a DATA-ONLY
        mesh whose data axis divides the batch.  On a multi-axis (TP) mesh
        the route's in_specs P('data', None, ...) would force the
        activation replicated over every other axis — channel-sharded
        activations would be all-gathered over 'model', worse than the jnp
        path where GSPMD keeps stats channel-sharded with zero activation
        traffic.  Shared with tools/bn_experiment's fail-loud guard so the
        two cannot drift."""
        from ..utils.engine import Engine
        return (mesh is not None and Engine.DATA_AXIS in mesh.axis_names
                and mesh.shape[Engine.DATA_AXIS] == mesh.size
                and batch_rows % mesh.shape[Engine.DATA_AXIS] == 0)

    def _apply_pallas(self, params, state, x, axes, interpret):
        from ..ops.batchnorm import bn_train
        y, mean, var = bn_train(x, params["weight"], params["bias"],
                                self.eps, 1024, interpret)
        n = 1
        for ax in axes:
            n *= x.shape[ax]
        return y, self._ema_update(state, mean, var, n)

    def _apply_pallas_sync(self, params, state, x, axis_name, interpret):
        from ..ops.batchnorm import bn_train_sync
        y, mean, var = bn_train_sync(x, params["weight"], params["bias"],
                                     self.eps, axis_name, 1024, interpret)
        n = 1
        for d in x.shape[:-1]:
            n *= d
        n = n * lax.psum(1, axis_name)
        return y, self._ema_update(state, mean, var, n)

    def _apply_pallas_shardmap(self, params, state, x, mesh, interpret):
        """Kernel-inside-shard_map sync-BN over the mesh data axis: the
        per-shard stat kernels run on each chip's local rows; the only
        cross-chip traffic is the psum of per-channel (sum, sumsq) /
        (sum dy, sum dy*xhat) vectors — the same collective the GSPMD
        lowering of the jnp path inserts."""
        from jax.sharding import PartitionSpec as P

        from ..ops.batchnorm import bn_train_sync
        from ..utils.compat import shard_map_unchecked
        from ..utils.engine import Engine

        axis = Engine.DATA_AXIS
        xspec = P(axis, *([None] * (x.ndim - 1)))
        def body(xl, w, b):  # custom_vjp: nondiff args must be positional
            return bn_train_sync(xl, w, b, self.eps, axis, 1024, interpret)
        y, mean, var = shard_map_unchecked(
            body, mesh=mesh, in_specs=(xspec, P(None), P(None)),
            out_specs=(xspec, P(None), P(None)))(
            x, params["weight"], params["bias"])
        n = 1
        for d in x.shape[:-1]:  # x is the global array here
            n *= d
        return y, self._ema_update(state, mean, var, n)

    def _apply_fused(self, params, state, x, mean, var, axes):
        n = 1
        for ax in axes:
            n *= x.shape[ax]
        y = _fused_bn_train(self.eps, x, params["weight"], params["bias"])
        return y, self._ema_update(state, mean, var, n)


class SpatialBatchNormalization(BatchNormalization):
    """BN over NHWC images: reduces over (N, H, W), per-channel stats
    (nn/SpatialBatchNormalization.scala).  Identical code path — the feature axis
    is last either way."""


class LayerNorm(Module):

    PARAM_ROLES = {"weight": "norm_scale", "bias": "norm_scale"}
    """Layer normalization over the last axis (net-new vs the 2017
    reference — required by the transformer/long-context capability,
    SURVEY.md §7; companion to nn/attention.MultiHeadAttention).  Stats in
    f32 regardless of the compute dtype, per-feature affine like BN."""

    def __init__(self, n_output: int, eps: float = 1e-5,
                 affine: bool = True):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.affine = affine

    def _init(self, rng):
        if not self.affine:
            return {}
        dt = get_policy().param_dtype
        return {"weight": jnp.ones((self.n_output,), dt),
                "bias": jnp.zeros((self.n_output,), dt)}

    def _apply(self, params, x):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["weight"].astype(jnp.float32) + \
                params["bias"].astype(jnp.float32)
        return y.astype(x.dtype)


class Normalize(Module):
    """L_p-normalize along the feature axis (nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p, self.eps = p, eps

    def _apply(self, params, x):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1, keepdims=True) ** (1.0 / self.p)
        return x / (norm + self.eps)


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels (nn/SpatialCrossMapLRN.scala):
    y = x / (k + alpha/size * sum_{local} x^2)^beta over NHWC channels."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def _apply(self, params, x):
        half = self.size // 2
        sq = jnp.square(x)
        # sum over a sliding window along the channel axis
        summed = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1,) * (x.ndim - 1) + (self.size,),
            window_strides=(1,) * x.ndim,
            padding=((0, 0),) * (x.ndim - 1) + ((half, self.size - half - 1),))
        denom = (self.k + self.alpha / self.size * summed) ** self.beta
        return x / denom


def _gaussian_kernel(size: int, dtype=jnp.float32):
    half = (size - 1) / 2.0
    xs = jnp.arange(size, dtype=dtype) - half
    sigma = size / 4.0 if size > 1 else 1.0
    k = jnp.exp(-jnp.square(xs) / (2 * sigma * sigma))
    return k / jnp.sum(k)


class SpatialWithinChannelLRN(Module):
    """LRN within each channel over a spatial window
    (nn/SpatialWithinChannelLRN.scala)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75):
        super().__init__()
        self.size, self.alpha, self.beta = size, alpha, beta

    def _apply(self, params, x):
        half = self.size // 2
        pad = (half, self.size - half - 1)
        mean_sq = lax.reduce_window(
            jnp.square(x), 0.0, lax.add,
            window_dimensions=(1, self.size, self.size, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), pad, pad, (0, 0))) / (self.size * self.size)
        return x / (1.0 + self.alpha * mean_sq) ** self.beta


class _GaussianBlur(Module):
    """Depthwise gaussian smoothing helper for the subtractive/divisive norms."""

    def __init__(self, size: int, n_channels: int):
        super().__init__()
        self.size, self.n_channels = size, n_channels

    def blur(self, x):
        k1 = _gaussian_kernel(self.size, x.dtype)
        kern = jnp.outer(k1, k1)[..., None, None]           # (s, s, 1, 1)
        kern = jnp.tile(kern, (1, 1, 1, x.shape[-1]))        # depthwise
        half = self.size // 2
        pad = (half, self.size - half - 1)
        return lax.conv_general_dilated(
            x, kern, (1, 1), [pad, pad],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])


class SpatialSubtractiveNormalization(_GaussianBlur):
    """Subtract the local (gaussian-weighted) mean
    (nn/SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel_size: int = 9):
        super().__init__(kernel_size, n_input_plane)

    def _apply(self, params, x):
        # blur() is per-channel normalized; the mean over channels completes
        # the cross-plane local mean (sum over planes / nInputPlane)
        return x - jnp.mean(self.blur(x), axis=-1, keepdims=True)


class SpatialDivisiveNormalization(_GaussianBlur):
    """Divide by the local standard deviation
    (nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel_size: int = 9,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__(kernel_size, n_input_plane)
        self.threshold, self.thresval = threshold, thresval

    def _apply(self, params, x):
        local_sq = self.blur(jnp.square(x))
        std = jnp.sqrt(jnp.maximum(
            jnp.mean(local_sq, axis=-1, keepdims=True), 0.0))
        std = jnp.where(std < self.threshold, self.thresval, std)
        return x / std


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization
    (nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel_size: int = 9,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel_size)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel_size,
                                                threshold, thresval)

    def _apply(self, params, x):
        return self.div._apply({}, self.sub._apply({}, x))
