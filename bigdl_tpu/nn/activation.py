"""Activation layers.

Reference: one file per activation under BigDL `nn/` — ReLU.scala, ReLU6.scala,
PReLU.scala, RReLU.scala, LeakyReLU.scala, ELU.scala, Tanh.scala, TanhShrink.scala,
Sigmoid.scala, SoftMax.scala, SoftMin.scala, SoftPlus.scala, SoftSign.scala,
SoftShrink.scala, HardShrink.scala, HardTanh.scala, Threshold.scala,
LogSoftMax.scala, LogSigmoid.scala.

TPU-native notes: every activation is a pure elementwise map that XLA fuses into the
surrounding matmul/conv — there is no per-op dispatch to a vendor library as in the
reference's MKL VML path (tensor/TensorNumeric.scala:229-312).  `inplace` flags from
the reference are meaningless under XLA (buffer reuse is the compiler's job) and are
accepted-and-ignored for API parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Module

__all__ = ["ReLU", "ReLU6", "PReLU", "RReLU", "LeakyReLU", "ELU", "Tanh",
           "TanhShrink", "Sigmoid", "SoftMax", "SoftMin", "SoftPlus", "SoftSign",
           "SoftShrink", "HardShrink", "HardTanh", "Threshold", "LogSoftMax",
           "LogSigmoid"]


class ReLU(Module):
    def __init__(self, ip: bool = False):
        super().__init__()

    def _apply(self, params, x):
        return jax.nn.relu(x)


class ReLU6(Module):
    def __init__(self, ip: bool = False):
        super().__init__()

    def _apply(self, params, x):
        return jnp.clip(x, 0.0, 6.0)


class PReLU(Module):

    PARAM_ROLES = {"weight": "elementwise"}
    """Learnable leaky slope; n_output_plane=0 means one shared scalar
    (nn/PReLU.scala)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane

    def _init(self, rng):
        n = max(self.n_output_plane, 1)
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}

    def _apply(self, params, x):
        w = params["weight"]
        if self.n_output_plane == 0:
            a = w[0]
        else:
            a = w.reshape((1,) * (x.ndim - 1) + (-1,))  # per-channel, NHWC
        return jnp.where(x >= 0, x, a * x)


class RReLU(Module):
    """Randomized leaky ReLU (nn/RReLU.scala): slope ~ U(lower, upper) in training,
    fixed mean slope in eval."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 ip: bool = False):
        super().__init__()
        self.lower, self.upper = lower, upper

    def apply(self, params, state, x, *, training=False, rng=None):
        if training and rng is not None:
            a = jax.random.uniform(rng, x.shape, x.dtype, self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x), state


class LeakyReLU(Module):
    def __init__(self, negval: float = 0.01, inplace: bool = False):
        super().__init__()
        self.negval = negval

    def _apply(self, params, x):
        return jnp.where(x >= 0, x, self.negval * x)


class ELU(Module):
    def __init__(self, alpha: float = 1.0, inplace: bool = False):
        super().__init__()
        self.alpha = alpha

    def _apply(self, params, x):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class Tanh(Module):
    def _apply(self, params, x):
        return jnp.tanh(x)


class TanhShrink(Module):
    def _apply(self, params, x):
        return x - jnp.tanh(x)


class Sigmoid(Module):
    def _apply(self, params, x):
        return jax.nn.sigmoid(x)


class SoftMax(Module):
    """Softmax over the last (feature) axis (nn/SoftMax.scala)."""

    def _apply(self, params, x):
        return jax.nn.softmax(x, axis=-1)


class SoftMin(Module):
    def _apply(self, params, x):
        return jax.nn.softmax(-x, axis=-1)


class SoftPlus(Module):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def _apply(self, params, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(Module):
    def _apply(self, params, x):
        return x / (1.0 + jnp.abs(x))


class SoftShrink(Module):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def _apply(self, params, x):
        return jnp.where(x > self.lam, x - self.lam,
                         jnp.where(x < -self.lam, x + self.lam, 0.0))


class HardShrink(Module):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def _apply(self, params, x):
        return jnp.where(jnp.abs(x) > self.lam, x, 0.0)


class HardTanh(Module):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 inplace: bool = False):
        super().__init__()
        self.min_value, self.max_value = min_value, max_value

    def _apply(self, params, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Threshold(Module):
    """x if x > th else value (nn/Threshold.scala)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__()
        # ip is semantically a no-op here (functional framework), but it is
        # part of the reference wire format — keep it so save/load through
        # interop.bigdl round-trips the flag for JVM consumers
        self.th, self.v, self.ip = th, v, bool(ip)

    def _apply(self, params, x):
        return jnp.where(x > self.th, x, self.v)


class LogSoftMax(Module):
    def _apply(self, params, x):
        return jax.nn.log_softmax(x, axis=-1)


class LogSigmoid(Module):
    def _apply(self, params, x):
        return jax.nn.log_sigmoid(x)


class GELU(Module):
    """Gaussian-error linear unit (net-new vs the 2017 reference; the
    transformer MLP activation — companion to nn/attention and
    nn.LayerNorm)."""

    def _apply(self, params, x):
        return jax.nn.gelu(x)
