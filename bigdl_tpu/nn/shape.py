"""Shape-manipulation layers.

Reference: one file each under BigDL `nn/`: Reshape.scala, InferReshape.scala,
View.scala, Transpose.scala, Replicate.scala, Squeeze.scala, Unsqueeze.scala,
Select.scala, Narrow.scala, Index.scala, MaskedSelect.scala, Reverse.scala,
Padding.scala, SpatialZeroPadding.scala, Contiguous.scala.

TPU-native notes: all of these are metadata ops under XLA (free or fused).  Axis
arguments are 0-based over the full tensor INCLUDING batch; the reference's
1-based-over-non-batch convention is documented per class.  `MaskedSelect` is the
one dynamic-shape op — under jit it returns a fixed-size output via the
where-and-fill idiom, with the true count as an aux (data-dependent shapes cannot
exist in a compiled TPU program).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .module import Module

__all__ = ["Reshape", "InferReshape", "View", "Transpose", "Replicate", "Squeeze",
           "Unsqueeze", "Select", "Narrow", "Index", "MaskedSelect", "Reverse",
           "Padding", "SpatialZeroPadding", "Contiguous"]


class Reshape(Module):
    """Reshape the non-batch dims to `size` (nn/Reshape.scala); batch_mode=None
    auto-detects like the reference, True forces keeping dim0 as batch."""

    def __init__(self, size, batch_mode: bool = True):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def _apply(self, params, x):
        if self.batch_mode:
            return x.reshape((x.shape[0],) + self.size)
        return x.reshape(self.size)


class InferReshape(Module):
    """Reshape with -1 (inferred) and 0 (copy input dim) entries
    (nn/InferReshape.scala)."""

    def __init__(self, size, batch_mode: bool = False):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def _apply(self, params, x):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            out.append(in_shape[i] if s == 0 else s)
        if self.batch_mode:
            return x.reshape((x.shape[0],) + tuple(out))
        return x.reshape(tuple(out))


class View(Module):
    """nn/View.scala — reshape keeping total element count; sizes may contain -1."""

    def __init__(self, *sizes):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(int(s) for s in sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n):
        self.num_input_dims = n
        return self

    def _apply(self, params, x):
        # batch-mode heuristic like the reference: if element counts differ by the
        # batch factor, keep dim0
        n_view = int(np.prod([s for s in self.sizes if s > 0]))
        if -1 in self.sizes or x.size != n_view:
            return x.reshape((x.shape[0],) + self.sizes)
        return x.reshape(self.sizes)


class Transpose(Module):
    """Swap listed axis pairs in order (nn/Transpose.scala). 0-based axes."""

    def __init__(self, permutations):
        super().__init__()
        self.permutations = [tuple(p) for p in permutations]

    def _apply(self, params, x):
        perm = list(range(x.ndim))
        for a, b in self.permutations:
            perm[a], perm[b] = perm[b], perm[a]
        return jnp.transpose(x, perm)


class Replicate(Module):
    """Insert a new axis of size n_features at `dim` (nn/Replicate.scala)."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = None):
        super().__init__()
        self.n_features, self.dim = n_features, dim

    def _apply(self, params, x):
        y = jnp.expand_dims(x, self.dim)
        reps = [1] * y.ndim
        reps[self.dim] = self.n_features
        return jnp.tile(y, reps)


class Squeeze(Module):
    """Drop size-1 dims (nn/Squeeze.scala); dim=None squeezes all."""

    def __init__(self, dim: int = None, num_input_dims: int = None):
        super().__init__()
        self.dim = dim

    def _apply(self, params, x):
        return jnp.squeeze(x, self.dim) if self.dim is not None else jnp.squeeze(x)


class Unsqueeze(Module):
    def __init__(self, pos: int, num_input_dims: int = None):
        super().__init__()
        self.pos = pos

    def _apply(self, params, x):
        return jnp.expand_dims(x, self.pos)


class Select(Module):
    """Slice index `index` off axis `dim` (nn/Select.scala). 0-based; negative
    indices count from the end like numpy."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self.dim, self.index = dim, index

    def _apply(self, params, x):
        return jnp.take(x, self.index, axis=self.dim)


class Narrow(Module):
    """Slice [offset, offset+length) along `dim` (nn/Narrow.scala); negative
    length means 'to the end minus |length|-1' like the reference."""

    def __init__(self, dim: int, offset: int, length: int = 1):
        super().__init__()
        self.dim, self.offset, self.length = dim, offset, length

    def _apply(self, params, x):
        length = self.length
        if length < 0:
            length = x.shape[self.dim] - self.offset + length + 1
        idx = [slice(None)] * x.ndim
        idx[self.dim] = slice(self.offset, self.offset + length)
        return x[tuple(idx)]


class Index(Module):
    """Index one tensor by another along `dim` (nn/Index.scala).
    Input: [tensor, indices]."""

    def __init__(self, dim: int):
        super().__init__()
        self.dim = dim

    def _apply(self, params, inputs):
        t, idx = inputs[0], inputs[1]
        return jnp.take(t, idx.astype(jnp.int32), axis=self.dim)


class MaskedSelect(Module):
    """nn/MaskedSelect.scala — select elements where mask != 0.

    Outside jit returns the compacted 1-D array (exact reference semantics).
    Inside jit (traced), returns a fixed-length vector of the masked values
    front-packed and zero-padded, since XLA requires static shapes.
    """

    def _apply(self, params, inputs):
        t, mask = inputs[0], inputs[1]
        mask = mask.astype(bool)
        if isinstance(jnp.asarray(t), jax.core.Tracer):
            flat_t, flat_m = t.reshape(-1), mask.reshape(-1)
            order = jnp.argsort(~flat_m, stable=True)
            packed = jnp.where(flat_m[order], flat_t[order], 0.0)
            return packed
        return t[mask]


class Reverse(Module):
    """Reverse along `dim` (nn/Reverse.scala). 0-based."""

    def __init__(self, dimension: int = 0):
        super().__init__()
        self.dimension = dimension

    def _apply(self, params, x):
        return jnp.flip(x, self.dimension)


class Padding(Module):
    """Pad `pad` entries (negative = front) along `dim` with `value`
    (nn/Padding.scala)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int = 0,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim, self.pad, self.value = dim, pad, value

    def _apply(self, params, x):
        widths = [(0, 0)] * x.ndim
        widths[self.dim] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value)


class SpatialZeroPadding(Module):
    """Zero-pad H/W of an NHWC tensor (nn/SpatialZeroPadding.scala)."""

    def __init__(self, pad_left: int, pad_right: int = None, pad_top: int = None,
                 pad_bottom: int = None):
        super().__init__()
        self.l = pad_left
        self.r = pad_right if pad_right is not None else pad_left
        self.t = pad_top if pad_top is not None else pad_left
        self.b = pad_bottom if pad_bottom is not None else pad_left

    def _apply(self, params, x):
        return jnp.pad(x, [(0, 0), (self.t, self.b), (self.l, self.r), (0, 0)])


class Contiguous(Module):
    """nn/Contiguous.scala — no-op under XLA (layout is the compiler's)."""

    def _apply(self, params, x):
        return x
