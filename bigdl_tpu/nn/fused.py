"""Cross-layer fusion rewrites (opt-in).

`ConvBN` fuses an adjacent (1x1 stride-1 SpatialConvolution,
SpatialBatchNormalization) pair so the BN batch statistics are accumulated
in the producing matmul's epilogue (ops/convbn.py), deleting the separate
stat read of the conv output — the round-4 verdict's untried HBM lever for
the BN-bound ResNet-50 train MFU.

`ConvBNAddReLU` widens the same fusion to the ResNet residual tail:
ConcatTable(branch ending conv1x1+BN, shortcut) -> CAddTable -> ReLU
collapses to one `ops.convbn.fused_conv_bn_add_relu_train` call, so the
block's closing matmul, BN stats, shortcut add, and ReLU — plus their
backward — are a single kernel + elementwise epilogue instead of four
module boundaries each re-reading the activation.

The reference performs analogous whole-graph rewrites for its quantized
path (bigdl/nn/Module.scala `quantize()`, replacing Conv/Linear with
quantized twins in place); here the rewrite is `fuse_conv_bn(container)`,
walking containers and substituting `ConvBN(conv, bn)` for eligible pairs.
Run it BEFORE `build()`/loading: the fusion nests the pair's two param
entries one level deeper, so param trees built before the rewrite do not
line up.

ConvBN subclasses Sequential, so its params/state are exactly the pair's
[conv, bn] list entries and every container facility (get_parameters,
checkpoint traversal, repr) works unchanged.  When the fused path cannot
engage (eval mode, GPU backend, a multi-axis/TP mesh, non-affine BN)
it falls back to the children's own apply — numerics are identical
either way (parity-tested in tests/test_convbn.py).  On a DATA-ONLY
mesh the kernel runs per shard inside shard_map with psum'd epilogue
stats (same construction as BatchNormalization's pallas mesh route).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import config
from .containers import ConcatTable, Sequential
from .conv import SpatialConvolution
from .module import Container
from .normalization import SpatialBatchNormalization

__all__ = ["ConvBN", "ConvBNAddReLU", "fuse_conv_bn"]


def _fusable(conv, bn) -> bool:
    return (isinstance(conv, SpatialConvolution)
            and type(conv) is SpatialConvolution  # not Map/Share subclasses
            and isinstance(bn, SpatialBatchNormalization)
            and conv.kernel == (1, 1) and conv.stride == (1, 1)
            and conv.pad == (0, 0) and conv.n_group == 1
            and bn.affine and bn.sync_axis is None
            and conv.n_output_plane == bn.n_output)


def _engagement(training: bool, batch_rows: int):
    """Shared fused-path gate for ConvBN / ConvBNAddReLU: returns
    (engaged, mesh, interpret).  Engagement mirrors
    BatchNormalization._route_pallas.  Off-TPU the kernels would run in
    interpret mode — orders of magnitude slower — so that needs the
    explicit BN_IMPL=pallas_interpret opt-in (tests/CPU smoke), never
    silence."""
    from ..utils.platform import backend_kind
    backend = backend_kind()  # resolves TPU plugin names like 'axon'
    interpret_req = config.get_str("BN_IMPL", "") == "pallas_interpret"
    multi = jax.device_count() > 1
    mesh = None
    if multi and (interpret_req or backend == "tpu"):
        # multi-device: the opaque pallas_call cannot be partitioned by
        # GSPMD directly, but on a data-only Engine mesh the kernel
        # runs per shard inside shard_map with psum'd epilogue stats —
        # identical sync-BN semantics, matmul fusion intact.  Other
        # multi-device shapes (TP meshes, no mesh) fall back to the
        # children.
        from ..utils.engine import Engine
        if SpatialBatchNormalization.shardmap_route_engages(
                Engine._mesh, batch_rows):
            mesh = Engine._mesh
    engaged = training and (mesh is not None or interpret_req
                            or (backend == "tpu" and not multi))
    return engaged, mesh, interpret_req or backend != "tpu"


class ConvBN(Sequential):
    """Fused 1x1-conv + training-mode BN (see module docstring)."""

    def __init__(self, conv: SpatialConvolution,
                 bn: SpatialBatchNormalization):
        assert _fusable(conv, bn), (conv, bn)
        super().__init__(conv, bn)

    def apply(self, params, state, x, *, training=False, rng=None):
        conv, bn = self.modules
        engaged, mesh, interpret = _engagement(training, x.shape[0])
        if not engaged:
            return super().apply(params, state, x, training=training,
                                 rng=rng)
        from ..common import get_policy
        from ..ops.convbn import fused_conv_bn_train

        conv_p, bn_p = params
        n, h, w_, k = x.shape
        c = get_policy().compute_dtype  # same cast the unfused conv makes
        w2 = conv_p["weight"].reshape(k, conv.n_output_plane).astype(c)

        def run(xl, w2, cbias, gamma, beta, axis):
            r = xl.shape[0] * h * w_
            z2, mean, var = fused_conv_bn_train(
                xl.reshape(r, k).astype(c), w2, cbias, gamma, beta,
                bn.eps, interpret, axis)
            return z2.reshape(xl.shape[0], h, w_, -1), mean, var

        args = (x, w2, conv_p.get("bias"), bn_p["weight"], bn_p["bias"])
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            from ..utils.compat import shard_map_unchecked
            from ..utils.engine import Engine
            axis = Engine.DATA_AXIS
            xspec = P(axis, None, None, None)
            vspec = P(None)
            z, mean, var = shard_map_unchecked(
                lambda *a: run(*a, axis),
                mesh=mesh,
                in_specs=(xspec, vspec, vspec, vspec, vspec),
                out_specs=(xspec, vspec, vspec))(*args)
        else:
            z, mean, var = run(*args, None)
        new_bn_state = bn._ema_update(state[1], mean, var, n * h * w_)
        return z, [state[0], new_bn_state]


class ConvBNAddReLU(Container):
    """Fused residual-unit tail: the branch's closing (1x1 conv, BN) plus
    the shortcut add and block ReLU, lowered through
    `ops.convbn.fused_conv_bn_add_relu_train` so the whole tail is one
    matmul + one elementwise epilogue (stats in the matmul, relu mask
    recomputed in the backward).

    Children (in param order): [head, conv, bn, shortcut] — `head` is the
    branch minus its last conv+bn pair, `shortcut` the residual path; both
    run unfused.  Rewritten in by `fuse_conv_bn` from the reference block
    shape ConcatTable(branch, shortcut) -> CAddTable -> ReLU
    (models/resnet.py `_residual`).  When the fused path cannot engage
    (eval mode, CPU without the interpret opt-in, TP meshes, or a shortcut
    whose output shape does not match the conv's) it computes the exact
    unfused composition: relu(bn(conv(head(x))) + shortcut(x)).
    """

    def __init__(self, head: Sequential, conv: SpatialConvolution,
                 bn: SpatialBatchNormalization, shortcut):
        assert _fusable(conv, bn), (conv, bn)
        super().__init__(head, conv, bn, shortcut)

    def apply(self, params, state, x, *, training=False, rng=None):
        head, conv, bn, shortcut = self.modules
        rngs = self._split_rng(rng)
        h, new_sh = head.apply(params[0], state[0], x, training=training,
                               rng=rngs[0])
        r, new_ssc = shortcut.apply(params[3], state[3], x,
                                    training=training, rng=rngs[3])
        n, hh, ww, k = h.shape
        engaged, mesh, interpret = _engagement(training, h.shape[0])
        if engaged and tuple(r.shape) != (n, hh, ww, conv.n_output_plane):
            engaged = False  # type-A shortcuts can disagree mid-rewrite
        if not engaged:
            y, new_sc = conv.apply(params[1], state[1], h,
                                   training=training, rng=rngs[1])
            y, new_sb = bn.apply(params[2], state[2], y,
                                 training=training, rng=rngs[2])
            z = jax.nn.relu(y + r)  # CAddTable -> ReLU, verbatim
            return z, [new_sh, new_sc, new_sb, new_ssc]
        from ..common import get_policy
        from ..ops.convbn import fused_conv_bn_add_relu_train

        conv_p, bn_p = params[1], params[2]
        c = get_policy().compute_dtype
        w2 = conv_p["weight"].reshape(k, conv.n_output_plane).astype(c)

        def run(hl, rl, w2, cbias, gamma, beta, axis):
            rows = hl.shape[0] * hh * ww
            z2, mean, var = fused_conv_bn_add_relu_train(
                hl.reshape(rows, k).astype(c), w2, cbias, gamma, beta,
                rl.reshape(rows, conv.n_output_plane).astype(c),
                bn.eps, interpret, axis)
            return z2.reshape(hl.shape[0], hh, ww, -1), mean, var

        args = (h, r, w2, conv_p.get("bias"), bn_p["weight"], bn_p["bias"])
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            from ..utils.compat import shard_map_unchecked
            from ..utils.engine import Engine
            axis = Engine.DATA_AXIS
            xspec = P(axis, None, None, None)
            vspec = P(None)
            z, mean, var = shard_map_unchecked(
                lambda *a: run(*a, axis),
                mesh=mesh,
                in_specs=(xspec, xspec, vspec, vspec, vspec, vspec),
                out_specs=(xspec, vspec, vspec))(*args)
        else:
            z, mean, var = run(*args, None)
        new_bn_state = bn._ema_update(state[2], mean, var, n * hh * ww)
        return z, [new_sh, state[1], new_bn_state, new_ssc]


def fuse_conv_bn(module):
    """Recursively replace eligible adjacent (conv, bn) pairs inside every
    container with ConvBN.  Mutates and returns `module`; run before
    build()/load (the rewrite re-nests the pair's param entries)."""
    if getattr(module, "params", None) is not None:
        raise ValueError(
            "fuse_conv_bn must run BEFORE build()/load: the rewrite "
            "re-nests the fused pairs' param entries, so an already-built "
            "param tree would no longer line up with the modules")
    return _fuse(module)


def _residual_tail(kids, i):
    """Match ConcatTable(branch ... conv1x1, bn; shortcut) -> CAddTable ->
    ReLU at kids[i] (models/resnet.py `_residual`); return the
    ConvBNAddReLU replacement or None."""
    from .activation import ReLU
    from .table_ops import CAddTable
    if i + 2 >= len(kids):
        return None
    ct, add, relu = kids[i], kids[i + 1], kids[i + 2]
    if not (isinstance(ct, ConcatTable) and len(ct.modules) == 2
            and type(add) is CAddTable and type(relu) is ReLU):
        return None
    branch, shortcut = ct.modules
    if not (isinstance(branch, Sequential) and len(branch.modules) >= 2
            and _fusable(branch.modules[-2], branch.modules[-1])):
        return None
    head = _fuse(Sequential(*branch.modules[:-2]))
    return ConvBNAddReLU(head, branch.modules[-2], branch.modules[-1],
                         _fuse(shortcut))


def _fuse(module):
    if isinstance(module, (ConvBN, ConvBNAddReLU)):
        return module
    if isinstance(module, Container):
        kids = module.modules
        if isinstance(module, Sequential):
            fused, i = [], 0
            while i < len(kids):
                tail = _residual_tail(kids, i)
                if tail is not None:
                    fused.append(tail)
                    i += 3
                elif i + 1 < len(kids) and _fusable(kids[i], kids[i + 1]):
                    fused.append(ConvBN(kids[i], kids[i + 1]))
                    i += 2
                else:
                    fused.append(_fuse(kids[i]))
                    i += 1
            module.modules = fused
        else:
            module.modules = [_fuse(m) for m in kids]
    return module
