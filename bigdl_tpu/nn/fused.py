"""Cross-layer fusion rewrites (opt-in).

`ConvBN` fuses an adjacent (1x1 stride-1 SpatialConvolution,
SpatialBatchNormalization) pair so the BN batch statistics are accumulated
in the producing matmul's epilogue (ops/convbn.py), deleting the separate
stat read of the conv output — the round-4 verdict's untried HBM lever for
the BN-bound ResNet-50 train MFU.

The reference performs analogous whole-graph rewrites for its quantized
path (bigdl/nn/Module.scala `quantize()`, replacing Conv/Linear with
quantized twins in place); here the rewrite is `fuse_conv_bn(container)`,
walking containers and substituting `ConvBN(conv, bn)` for eligible pairs.
Run it BEFORE `build()`/loading: the fusion nests the pair's two param
entries one level deeper, so param trees built before the rewrite do not
line up.

ConvBN subclasses Sequential, so its params/state are exactly the pair's
[conv, bn] list entries and every container facility (get_parameters,
checkpoint traversal, repr) works unchanged.  When the fused path cannot
engage (eval mode, GPU backend, a multi-axis/TP mesh, non-affine BN)
it falls back to the children's own apply — numerics are identical
either way (parity-tested in tests/test_convbn.py).  On a DATA-ONLY
mesh the kernel runs per shard inside shard_map with psum'd epilogue
stats (same construction as BatchNormalization's pallas mesh route).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import config
from .containers import Sequential
from .conv import SpatialConvolution
from .module import Container
from .normalization import SpatialBatchNormalization

__all__ = ["ConvBN", "fuse_conv_bn"]


def _fusable(conv, bn) -> bool:
    return (isinstance(conv, SpatialConvolution)
            and type(conv) is SpatialConvolution  # not Map/Share subclasses
            and isinstance(bn, SpatialBatchNormalization)
            and conv.kernel == (1, 1) and conv.stride == (1, 1)
            and conv.pad == (0, 0) and conv.n_group == 1
            and bn.affine and bn.sync_axis is None
            and conv.n_output_plane == bn.n_output)


class ConvBN(Sequential):
    """Fused 1x1-conv + training-mode BN (see module docstring)."""

    def __init__(self, conv: SpatialConvolution,
                 bn: SpatialBatchNormalization):
        assert _fusable(conv, bn), (conv, bn)
        super().__init__(conv, bn)

    def apply(self, params, state, x, *, training=False, rng=None):
        conv, bn = self.modules
        from ..utils.platform import backend_kind
        backend = backend_kind()  # resolves TPU plugin names like 'axon'
        # engagement mirrors BatchNormalization._route_pallas.  Off-TPU
        # the kernels would run in interpret mode — orders of magnitude
        # slower — so that needs the explicit BN_IMPL=pallas_interpret
        # opt-in (tests/CPU smoke), never silence.
        interpret_req = config.get_str("BN_IMPL", "") == "pallas_interpret"
        multi = jax.device_count() > 1
        mesh = None
        if multi and (interpret_req or backend == "tpu"):
            # multi-device: the opaque pallas_call cannot be partitioned by
            # GSPMD directly, but on a data-only Engine mesh the kernel
            # runs per shard inside shard_map with psum'd epilogue stats —
            # identical sync-BN semantics, matmul fusion intact.  Other
            # multi-device shapes (TP meshes, no mesh) fall back to the
            # children.
            from ..utils.engine import Engine
            if SpatialBatchNormalization.shardmap_route_engages(
                    Engine._mesh, x.shape[0]):
                mesh = Engine._mesh
        if not training or not (
                mesh is not None
                or interpret_req
                or (backend == "tpu" and not multi)):
            return super().apply(params, state, x, training=training,
                                 rng=rng)
        from ..common import get_policy
        from ..ops.convbn import fused_conv_bn_train

        conv_p, bn_p = params
        n, h, w_, k = x.shape
        c = get_policy().compute_dtype  # same cast the unfused conv makes
        w2 = conv_p["weight"].reshape(k, conv.n_output_plane).astype(c)
        interpret = interpret_req or backend != "tpu"

        def run(xl, w2, cbias, gamma, beta, axis):
            r = xl.shape[0] * h * w_
            z2, mean, var = fused_conv_bn_train(
                xl.reshape(r, k).astype(c), w2, cbias, gamma, beta,
                bn.eps, interpret, axis)
            return z2.reshape(xl.shape[0], h, w_, -1), mean, var

        args = (x, w2, conv_p.get("bias"), bn_p["weight"], bn_p["bias"])
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            from ..utils.compat import shard_map_unchecked
            from ..utils.engine import Engine
            axis = Engine.DATA_AXIS
            xspec = P(axis, None, None, None)
            vspec = P(None)
            z, mean, var = shard_map_unchecked(
                lambda *a: run(*a, axis),
                mesh=mesh,
                in_specs=(xspec, vspec, vspec, vspec, vspec),
                out_specs=(xspec, vspec, vspec))(*args)
        else:
            z, mean, var = run(*args, None)
        new_bn_state = bn._ema_update(state[1], mean, var, n * h * w_)
        return z, [state[0], new_bn_state]


def fuse_conv_bn(module):
    """Recursively replace eligible adjacent (conv, bn) pairs inside every
    container with ConvBN.  Mutates and returns `module`; run before
    build()/load (the rewrite re-nests the pair's param entries)."""
    if getattr(module, "params", None) is not None:
        raise ValueError(
            "fuse_conv_bn must run BEFORE build()/load: the rewrite "
            "re-nests the fused pairs' param entries, so an already-built "
            "param tree would no longer line up with the modules")
    return _fuse(module)


def _fuse(module):
    if isinstance(module, ConvBN):
        return module
    if isinstance(module, Container):
        kids = module.modules
        if isinstance(module, Sequential):
            fused, i = [], 0
            while i < len(kids):
                if i + 1 < len(kids) and _fusable(kids[i], kids[i + 1]):
                    fused.append(ConvBN(kids[i], kids[i + 1]))
                    i += 2
                else:
                    fused.append(_fuse(kids[i]))
                    i += 1
            module.modules = fused
        else:
            module.modules = [_fuse(m) for m in kids]
    return module
