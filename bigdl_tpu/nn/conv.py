"""Convolution layers.

Reference: BigDL `nn/SpatialConvolution.scala:42` implements conv as explicit
im2col + MKL gemm scalar loops (`NNPrimitive.im2colFloat`,
SpatialConvolution.scala:470-530), parallelized over output frames with
`Engine.model.invoke` (:202).  `nn/SpatialDilatedConvolution.scala`,
`nn/SpatialFullConvolution.scala` (deconvolution), `nn/TemporalConvolution.scala`
(1-D), `nn/VolumetricConvolution.scala` (3-D), `nn/SpatialShareConvolution.scala`,
`nn/SpatialConvolutionMap.scala`.

TPU-native re-design: NO im2col port.  Every conv lowers to
`jax.lax.conv_general_dilated`, which XLA tiles directly onto the MXU; layout is
NHWC/HWIO (TPU-preferred), compute in the policy dtype (bf16) with float32
accumulation.  Groups map to `feature_group_count`; deconvolution maps to
`conv_transpose`-style lhs dilation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ..common import conv_accum_dtype, get_policy
from ..utils import config as _config
from .initialization import default_bias_init, default_weight_init
from .module import Module


def _pad_tiny_cin(x, w, n_group):
    """Zero-pad the input-channel axis of (x, w) up to a minimum width.

    XLA's TPU backend pathologically compiles the *backward* of convs whose
    input-channel count is far below the sublane granularity — grad(conv) at
    (512,28,28,1)x(5,5,1,6) has been observed to compile for 8+ minutes
    (docs/benchmarking.md, "small-channel conv backward").  The reference hits
    the same small-shape inefficiency in its im2col+gemm lowering and solves it
    by switching lowerings (nn/SpatialConvolution.scala:470-530); here the fix
    is shape-level: pad C_in with zero channels up to
    BIGDL_TPU_CONV_PAD_MIN_CIN (default 8, 0 disables).  Forward values are
    bit-identical (zero channels contribute nothing to the contraction), the
    input gradient is the slice-adjoint of the pad, and the padded weight
    gradients are discarded by the same slice — only the compiled program's
    shapes change.  Shape-generic (pads w's axis -2 and x's last axis), so it
    covers WIO/HWIO/DHWIO weights alike; every conv layer in this module calls
    it, including SpatialFullConvolution whose lhs-dilated *forward* is itself
    a gradient-conv-shaped program.

    Grouped convs pad too: the weight's axis -2 is already per-group
    (C_in/groups), and x's channel axis is padded per group block —
    (..., G*cpg) reshaped to (..., G, cpg), zero-padded to (..., G,
    min_cin), flattened back — so `feature_group_count` still divides and
    each group contracts over its own (zero-extended) channels.
    """
    min_cin = _config.get_int("CONV_PAD_MIN_CIN", 8)
    cpg = w.shape[-2]  # per-group input channels (HWIO stores C_in/groups)
    if min_cin <= 0 or cpg >= min_cin:
        return x, w
    extra = min_cin - cpg
    w = jnp.pad(w, [(0, 0)] * (w.ndim - 2) + [(0, extra), (0, 0)])
    if n_group == 1:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, extra)])
    else:
        shape = x.shape
        x = x.reshape(shape[:-1] + (n_group, cpg))
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, extra)])
        x = x.reshape(shape[:-1] + (n_group * min_cin,))
    return x, w


def _conv_route(w, n_group, lhs_dilation=None) -> str:
    """Per-shape lowering choice for tiny-C_in 2-D convs.

    Returns 'lax' (no rewrite — C_in is wide enough or the mitigation is
    off), 'pad' (zero-pad channels, the default mitigation), or 'matmul'
    (the im2col reshaped-matmul route, ops/convmm.py — opt-in via
    ``BIGDL_TPU_CONV_ROUTE=matmul``, which eliminates the pathological
    grad-of-conv program instead of padding around it).  Grouped and
    lhs-dilated convs always fall back to the pad: the matmul route covers
    the single-group correlation shape only.
    """
    min_cin = _config.get_int("CONV_PAD_MIN_CIN", 8)
    if min_cin <= 0 or w.shape[-2] >= min_cin:
        return "lax"
    mode = _config.get_str("CONV_ROUTE", "pad")
    if mode == "matmul" and n_group == 1 and lhs_dilation is None:
        return "matmul"
    if mode in ("lax", "off", "0"):
        return "lax"
    return "pad"

__all__ = ["SpatialConvolution", "SpatialDilatedConvolution",
           "SpatialFullConvolution", "TemporalConvolution",
           "VolumetricConvolution", "SpatialShareConvolution",
           "SpatialConvolutionMap"]


class SpatialConvolution(Module):
    """2-D convolution over NHWC input (reference: nn/SpatialConvolution.scala:42,
    which uses NCHW — layout re-designed for TPU).

    Weight: (kh, kw, cin/groups, cout) HWIO.  Argument order keeps the reference's
    (nInputPlane, nOutputPlane, kW, kH, dW, dH, padW, padH, nGroup) signature.
    """

    #: mesh-layout roles: HWIO kernels are tp-split on cout,
    #: fsdp-sliced on cin (parallel/layout)
    PARAM_ROLES = {"weight": "conv_kernel", "bias": "bias"}

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1,
                 propagate_back: bool = True, with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _weight_shape(self):
        kh, kw = self.kernel
        return (kh, kw, self.n_input_plane // self.n_group, self.n_output_plane)

    def _init(self, rng):
        kw_, kb = jax.random.split(rng)
        shape = self._weight_shape()
        fan_in = shape[0] * shape[1] * shape[2]
        fan_out = shape[0] * shape[1] * shape[3] // self.n_group
        winit = self.weight_initializer or default_weight_init
        binit = self.bias_initializer or default_bias_init
        p = {"weight": winit(kw_, shape, fan_in, fan_out, get_policy().param_dtype)}
        if self.with_bias:
            p["bias"] = binit(kb, (self.n_output_plane,), fan_in, fan_out,
                              get_policy().param_dtype)
        return p

    def _conv(self, x, w, lhs_dilation=None, rhs_dilation=None, padding=None):
        c = get_policy().compute_dtype
        pad_h, pad_w = self.pad
        if padding is None:
            # pad=-1 means SAME, as in the reference (SpatialConvolution
            # doc: "If padW/padH are -1, they will be computed such that
            # output has the same size as input")
            padding = ("SAME" if pad_h == -1 or pad_w == -1
                       else [(pad_h, pad_h), (pad_w, pad_w)])
        if _conv_route(w, self.n_group, lhs_dilation) == "matmul":
            from ..ops.convmm import conv2d_matmul, same_pads
            dil = tuple(rhs_dilation) if rhs_dilation else (1, 1)
            if padding == "SAME":
                padding = [same_pads(x.shape[1 + d],
                                     (w.shape[d] - 1) * dil[d] + 1,
                                     self.stride[d]) for d in range(2)]
            y = conv2d_matmul(x.astype(c), w.astype(c), tuple(self.stride),
                              tuple(tuple(p) for p in padding), dil)
        else:
            x, w = _pad_tiny_cin(x, w, self.n_group)
            y = lax.conv_general_dilated(
                x.astype(c), w.astype(c),
                window_strides=self.stride,
                padding=padding,
                lhs_dilation=lhs_dilation,
                rhs_dilation=rhs_dilation,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=self.n_group,
                preferred_element_type=conv_accum_dtype())
        # named so selective rematerialization (Optimizer.set_remat("conv_out"))
        # can save exactly the MXU outputs and recompute the cheap elementwise
        # tail (BN/ReLU/add) in the backward pass; a no-op otherwise
        return checkpoint_name(y.astype(c), "conv_out")

    def _apply(self, params, x):
        y = self._conv(x, params["weight"])
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class SpatialShareConvolution(SpatialConvolution):
    """Reference nn/SpatialShareConvolution.scala exists only to share im2col
    buffers between layers — meaningless under XLA (the compiler owns buffers), so
    it is a pure alias kept for API parity."""


class SpatialConvolutionMap(SpatialConvolution):
    """Convolution with a sparse input->output map connection table
    (reference: nn/SpatialConvolutionMap.scala; Torch's conn-table conv).

    TPU re-design: rather than per-connection scalar loops, keep a dense HWIO
    kernel and multiply by a static 0/1 connectivity mask — XLA folds the mask
    into the conv weights and the MXU still sees one dense conv.  Gradients of
    masked-out entries are zero, so they stay dead under training.

    `conn_table`: int array (n_connections, 2) of (input_map, output_map)
    pairs, 0-based.  Helpers `full/one_to_one/random` mirror the reference's
    table constructors.  Plane counts default to table-max+1; pass
    `n_input_plane`/`n_output_plane` explicitly when the table may not
    mention the highest map (e.g. sparse `random` tables).
    """

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 n_input_plane: int = None, n_output_plane: int = None):
        table = jnp.asarray(conn_table, dtype=jnp.int32)
        n_in = n_input_plane or int(table[:, 0].max()) + 1
        n_out = n_output_plane or int(table[:, 1].max()) + 1
        if int(table[:, 0].max()) >= n_in or int(table[:, 1].max()) >= n_out:
            raise ValueError("connection table indexes beyond plane counts")
        super().__init__(n_in, n_out, kernel_w, kernel_h, stride_w, stride_h,
                         pad_w, pad_h, 1, True, with_bias,
                         w_regularizer, b_regularizer)
        mask = jnp.zeros((n_in, n_out))
        mask = mask.at[table[:, 0], table[:, 1]].set(1.0)
        self._mask = mask[None, None]  # (1, 1, cin, cout) broadcast over kh,kw

    @staticmethod
    def full(n_in: int, n_out: int):
        """Fully-connected table (SpatialConvolutionMap.scala `full`)."""
        import numpy as _np
        return _np.stack(_np.meshgrid(_np.arange(n_in), _np.arange(n_out),
                                      indexing="ij"), -1).reshape(-1, 2)

    @staticmethod
    def one_to_one(n_features: int):
        """(SpatialConvolutionMap.scala `oneToOne`)."""
        import numpy as _np
        r = _np.arange(n_features)
        return _np.stack([r, r], -1)

    @staticmethod
    def random(n_in: int, n_out: int, n_to: int, seed: int = 0):
        """Each output map connects to `n_to` random input maps
        (SpatialConvolutionMap.scala `random`)."""
        import numpy as _np
        rng = _np.random.default_rng(seed)
        rows = []
        for o in range(n_out):
            for i in rng.choice(n_in, size=min(n_to, n_in), replace=False):
                rows.append((int(i), o))
        return _np.array(rows, dtype=_np.int32)

    def _apply(self, params, x):
        masked = {**params,
                  "weight": params["weight"] * self._mask.astype(
                      params["weight"].dtype)}
        return super()._apply(masked, x)


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous conv (nn/SpatialDilatedConvolution.scala) via rhs_dilation."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 dilation_w=1, dilation_h=1, with_bias=True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h, 1, True, with_bias,
                         w_regularizer, b_regularizer)
        self.dilation = (dilation_h, dilation_w)

    def _apply(self, params, x):
        y = self._conv(x, params["weight"], rhs_dilation=self.dilation)
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class SpatialFullConvolution(Module):
    """Transposed convolution / deconvolution (nn/SpatialFullConvolution.scala),
    via lhs (input) dilation — XLA lowers this as efficiently as a gradient conv.

    Output size: (in-1)*stride - 2*pad + kernel + adj.
    """

    PARAM_ROLES = {"weight": "conv_kernel", "bias": "bias"}

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0, adj_w=0, adj_h=0,
                 n_group=1, no_bias=False, w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.n_group = n_group
        self.with_bias = not no_bias

    def _init(self, rng):
        kw_, kb = jax.random.split(rng)
        kh, kw = self.kernel
        # stored like the forward conv of the reverse direction: HWIO with
        # I=n_input_plane/groups acting as the *input* of the transposed op
        shape = (kh, kw, self.n_input_plane // self.n_group, self.n_output_plane)
        fan_in = kh * kw * shape[2]
        winit = self.weight_initializer or default_weight_init
        binit = self.bias_initializer or default_bias_init
        p = {"weight": winit(kw_, shape, fan_in, fan_in, get_policy().param_dtype)}
        if self.with_bias:
            p["bias"] = binit(kb, (self.n_output_plane,), fan_in, fan_in,
                              get_policy().param_dtype)
        return p

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        ah, aw = self.adj
        w = params["weight"].astype(c)
        # flip spatial dims: transposed conv correlates with the flipped kernel
        w = w[::-1, ::-1, :, :]
        x, w = _pad_tiny_cin(x, w, self.n_group)
        y = lax.conv_general_dilated(
            x.astype(c), w,
            window_strides=(1, 1),
            padding=[(kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw)],
            lhs_dilation=(sh, sw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_group,
            preferred_element_type=conv_accum_dtype()).astype(c)
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class TemporalConvolution(Module):
    """1-D convolution over (batch, time, features) (nn/TemporalConvolution.scala).

    Weight stored as (kernel, in, out); lowers to conv_general_dilated with
    ("NWC", "WIO", "NWC") so the MXU still sees a big matmul.
    """

    PARAM_ROLES = {"weight": "conv_kernel", "bias": "bias"}

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1, propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w

    def _init(self, rng):
        kw_, kb = jax.random.split(rng)
        shape = (self.kernel_w, self.input_frame_size, self.output_frame_size)
        fan_in = self.kernel_w * self.input_frame_size
        winit = self.weight_initializer or default_weight_init
        binit = self.bias_initializer or default_bias_init
        return {
            "weight": winit(kw_, shape, fan_in, fan_in, get_policy().param_dtype),
            "bias": binit(kb, (self.output_frame_size,), fan_in, fan_in,
                          get_policy().param_dtype),
        }

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        x, w = _pad_tiny_cin(x, params["weight"], 1)
        y = lax.conv_general_dilated(
            x.astype(c), w.astype(c),
            window_strides=(self.stride_w,),
            padding=[(0, 0)],
            dimension_numbers=("NWC", "WIO", "NWC"),
            preferred_element_type=conv_accum_dtype()).astype(c)
        return y + params["bias"].astype(y.dtype)


class VolumetricConvolution(Module):
    """3-D convolution over (batch, depth, height, width, channels)
    (nn/VolumetricConvolution.scala; reference layout NCDHW → NDHWC here)."""

    PARAM_ROLES = {"weight": "conv_kernel", "bias": "bias"}

    def __init__(self, n_input_plane, n_output_plane, k_t, k_w, k_h,
                 d_t=1, d_w=1, d_h=1, pad_t=0, pad_w=0, pad_h=0,
                 with_bias=True, w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias

    def _init(self, rng):
        kw_, kb = jax.random.split(rng)
        kt, kh, kw = self.kernel
        shape = (kt, kh, kw, self.n_input_plane, self.n_output_plane)
        fan_in = kt * kh * kw * self.n_input_plane
        winit = self.weight_initializer or default_weight_init
        binit = self.bias_initializer or default_bias_init
        p = {"weight": winit(kw_, shape, fan_in, fan_in, get_policy().param_dtype)}
        if self.with_bias:
            p["bias"] = binit(kb, (self.n_output_plane,), fan_in, fan_in,
                              get_policy().param_dtype)
        return p

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        pt, ph, pw = self.pad
        x, w = _pad_tiny_cin(x, params["weight"], 1)
        y = lax.conv_general_dilated(
            x.astype(c), w.astype(c),
            window_strides=self.stride,
            padding=[(pt, pt), (ph, ph), (pw, pw)],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            preferred_element_type=conv_accum_dtype()).astype(c)
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y
