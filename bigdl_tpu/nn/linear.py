"""Dense / elementwise-parameter layers.

Reference: BigDL `nn/Linear.scala`, `nn/Bilinear.scala`, `nn/CMul.scala`,
`nn/CAdd.scala`, `nn/Mul.scala`, `nn/Add.scala`, `nn/MulConstant.scala`,
`nn/AddConstant.scala`.

TPU-native notes: Linear is the MXU workhorse — inputs/weights are cast to the
policy compute dtype (bf16 by default on TPU benches) with float32 accumulation
(`preferred_element_type`), replacing the reference's MKL `vsgemm` JNI call
(tensor/DenseTensorBLAS.scala:70 → TensorNumeric.scala:195).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common import get_policy
from .initialization import compute_fans, default_bias_init, default_weight_init
from .module import Module

__all__ = ["Linear", "Bilinear", "CMul", "CAdd", "Mul", "Add", "MulConstant",
           "AddConstant"]


class Linear(Module):
    """y = x W^T + b, weight shape (out, in) as in the reference (nn/Linear.scala)."""

    #: mesh-layout roles (parallel/layout): (out, in) weight is
    #: column-parallel over tp, fsdp-sliced on the input axis
    PARAM_ROLES = {"weight": "kernel_out", "bias": "bias"}

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _init(self, rng):
        kw, kb = jax.random.split(rng)
        shape = (self.output_size, self.input_size)
        fi, fo = compute_fans(shape)
        winit = self.weight_initializer or default_weight_init
        binit = self.bias_initializer or default_bias_init
        p = {"weight": winit(kw, shape, fi, fo, get_policy().param_dtype)}
        if self.with_bias:
            p["bias"] = binit(kb, (self.output_size,), fi, fo,
                              get_policy().param_dtype)
        return p

    def _apply(self, params, x):
        c = get_policy().compute_dtype
        y = jax.lax.dot_general(
            x.astype(c), params["weight"].astype(c).T,
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if self.with_bias:
            y = y + params["bias"]
        return y.astype(c)


class Bilinear(Module):
    """y_k = x1^T W_k x2 + b_k (nn/Bilinear.scala). Inputs: [x1, x2]."""

    PARAM_ROLES = {"weight": "kernel_out", "bias": "bias"}

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True):
        super().__init__()
        self.input_size1, self.input_size2 = input_size1, input_size2
        self.output_size = output_size
        self.bias_res = bias_res

    def _init(self, rng):
        kw, kb = jax.random.split(rng)
        shape = (self.output_size, self.input_size1, self.input_size2)
        stdv = 1.0 / (self.input_size1 ** 0.5)
        p = {"weight": jax.random.uniform(kw, shape, jnp.float32, -stdv, stdv)}
        if self.bias_res:
            p["bias"] = jax.random.uniform(kb, (self.output_size,), jnp.float32,
                                           -stdv, stdv)
        return p

    def _apply(self, params, inputs):
        x1, x2 = inputs[0], inputs[1]
        y = jnp.einsum("bi,kij,bj->bk", x1, params["weight"], x2)
        if self.bias_res:
            y = y + params["bias"]
        return y


class CMul(Module):
    """Learnable per-element scale broadcast over the batch (nn/CMul.scala)."""

    PARAM_ROLES = {"weight": "elementwise"}

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)

    def _init(self, rng):
        import numpy as np
        stdv = 1.0 / (np.prod(self.size) ** 0.5)
        return {"weight": jax.random.uniform(rng, self.size, jnp.float32,
                                             -stdv, stdv)}

    def _apply(self, params, x):
        return x * params["weight"]


class CAdd(Module):
    """Learnable per-element bias (nn/CAdd.scala)."""

    PARAM_ROLES = {"bias": "elementwise"}

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)

    def _init(self, rng):
        import numpy as np
        stdv = 1.0 / (np.prod(self.size) ** 0.5)
        return {"bias": jax.random.uniform(rng, self.size, jnp.float32,
                                           -stdv, stdv)}

    def _apply(self, params, x):
        return x + params["bias"]


class Mul(Module):
    """Single learnable scalar gain (nn/Mul.scala)."""

    PARAM_ROLES = {"weight": "scalar"}

    def _init(self, rng):
        return {"weight": jax.random.uniform(rng, (), jnp.float32, -1.0, 1.0)}

    def _apply(self, params, x):
        return x * params["weight"]


class Add(Module):
    """Learnable bias vector over the feature dim (nn/Add.scala)."""

    PARAM_ROLES = {"bias": "bias"}

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size

    def _init(self, rng):
        stdv = 1.0 / (self.input_size ** 0.5)
        return {"bias": jax.random.uniform(rng, (self.input_size,), jnp.float32,
                                           -stdv, stdv)}

    def _apply(self, params, x):
        return x + params["bias"]


class MulConstant(Module):
    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant = constant_scalar

    def _apply(self, params, x):
        return x * self.constant


class AddConstant(Module):
    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant = constant_scalar

    def _apply(self, params, x):
        return x + self.constant
