"""Pooling layers.

Reference: BigDL `nn/SpatialMaxPooling.scala`, `nn/SpatialAveragePooling.scala`,
`nn/VolumetricMaxPooling.scala`, `nn/RoiPooling.scala`, `nn/Nms.scala`.

TPU-native notes: pooling lowers to `lax.reduce_window`, which XLA maps onto the
VPU; the reference's explicit index-tracking max-pool backward (scalar loops) is
replaced by XLA's automatic `reduce_window` gradient (a select-and-scatter op).
NHWC layout; `ceil_mode` matches the reference's ceil/floor output-size switch.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .module import Module

__all__ = ["SpatialMaxPooling", "SpatialAveragePooling", "VolumetricMaxPooling",
           "RoiPooling"]


def _pool_pads(size, kernel, stride, pad, ceil_mode):
    """Per-dim (lo, hi) padding; hi is extended so the window count matches
    Torch's ceil/floor formula (SpatialMaxPooling.scala out-size logic).
    pad=-1 means TF-style SAME (mirrors SpatialConvolution's convention)."""
    if pad == -1:
        out = -(-size // stride)  # ceil(size / stride)
        total = max((out - 1) * stride + kernel - size, 0)
        return out, (total // 2, total - total // 2)
    if ceil_mode:
        out = int(np.ceil((size + 2 * pad - kernel) / stride)) + 1
        # Torch: last window must start inside the (padded) input
        if pad > 0 and (out - 1) * stride >= size + pad:
            out -= 1
    else:
        out = int(np.floor((size + 2 * pad - kernel) / stride)) + 1
    # extra hi padding so the last window fits; never negative (elements no
    # window covers are simply ignored — output size is unaffected)
    needed = (out - 1) * stride + kernel - size - pad
    return out, (pad, max(needed, 0))


class SpatialMaxPooling(Module):
    """Max pool over NHWC (nn/SpatialMaxPooling.scala). Signature keeps the
    reference's (kW, kH, dW, dH, padW, padH) order."""

    def __init__(self, k_w: int, k_h: int, d_w: int = None, d_h: int = None,
                 pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.kernel = (k_h, k_w)
        self.stride = (d_h or k_h, d_w or k_w)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _apply(self, params, x):
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        _, pad_h = _pool_pads(x.shape[1], kh, sh, ph, self.ceil_mode)
        _, pad_w = _pool_pads(x.shape[2], kw, sw, pw, self.ceil_mode)
        neg = (-np.inf if jnp.issubdtype(x.dtype, jnp.floating)
               else np.iinfo(x.dtype).min)
        return lax.reduce_window(
            x, neg, lax.max,
            window_dimensions=(1, kh, kw, 1),
            window_strides=(1, sh, sw, 1),
            padding=((0, 0), pad_h, pad_w, (0, 0)))


class SpatialAveragePooling(Module):
    """Average pool (nn/SpatialAveragePooling.scala).  `count_include_pad`
    matches the reference's divisor convention."""

    def __init__(self, k_w: int, k_h: int, d_w: int = None, d_h: int = None,
                 pad_w: int = 0, pad_h: int = 0, global_pooling: bool = False,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True):
        super().__init__()
        self.kernel = (k_h, k_w)
        self.stride = (d_h or k_h, d_w or k_w)
        self.pad = (pad_h, pad_w)
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide

    def ceil(self):
        self.ceil_mode = True
        return self

    def _apply(self, params, x):
        if self.global_pooling:
            kh, kw = x.shape[1], x.shape[2]
            sh, sw = kh, kw
            ph = pw = 0
        else:
            kh, kw = self.kernel
            sh, sw = self.stride
            ph, pw = self.pad
        _, pad_h = _pool_pads(x.shape[1], kh, sh, ph, self.ceil_mode)
        _, pad_w = _pool_pads(x.shape[2], kw, sw, pw, self.ceil_mode)
        summed = lax.reduce_window(
            x, 0.0, lax.add,
            window_dimensions=(1, kh, kw, 1),
            window_strides=(1, sh, sw, 1),
            padding=((0, 0), pad_h, pad_w, (0, 0)))
        if not self.divide:
            return summed
        if self.count_include_pad:
            return summed / (kh * kw)
        ones = jnp.ones((1, x.shape[1], x.shape[2], 1), x.dtype)
        counts = lax.reduce_window(
            ones, 0.0, lax.add,
            window_dimensions=(1, kh, kw, 1),
            window_strides=(1, sh, sw, 1),
            padding=((0, 0), pad_h, pad_w, (0, 0)))
        return summed / counts


class VolumetricMaxPooling(Module):
    """3-D max pool over NDHWC (nn/VolumetricMaxPooling.scala)."""

    def __init__(self, k_t, k_w, k_h, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0):
        super().__init__()
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)

    def _apply(self, params, x):
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        pt, ph, pw = self.pad
        return lax.reduce_window(
            x, -np.inf, lax.max,
            window_dimensions=(1, kt, kh, kw, 1),
            window_strides=(1, st, sh, sw, 1),
            padding=((0, 0), (pt, pt), (ph, ph), (pw, pw), (0, 0)))


class RoiPooling(Module):
    """Region-of-interest max pooling (nn/RoiPooling.scala).

    Input: [features NHWC, rois (R, 5) rows = (batch_idx, x1, y1, x2, y2)].
    Output: (R, pooled_h, pooled_w, C).  Static output shape (R fixed per batch)
    keeps it jit-compatible; implemented with gather + reduce_window-free max over
    dynamically sliced bins using vmap'd index arithmetic.
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float = 1.0):
        super().__init__()
        self.pooled_w, self.pooled_h = pooled_w, pooled_h
        self.spatial_scale = spatial_scale

    def _apply(self, params, inputs):
        feats, rois = inputs[0], inputs[1]
        H, W = feats.shape[1], feats.shape[2]
        ph, pw = self.pooled_h, self.pooled_w

        def pool_one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
            rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
            bin_h, bin_w = rh / ph, rw / pw
            fmap = feats[b]  # (H, W, C)
            ys = jnp.arange(H)[:, None]
            xs = jnp.arange(W)[None, :]

            def one_bin(i, j):
                hstart = jnp.floor(i * bin_h).astype(jnp.int32) + y1
                hend = jnp.ceil((i + 1) * bin_h).astype(jnp.int32) + y1
                wstart = jnp.floor(j * bin_w).astype(jnp.int32) + x1
                wend = jnp.ceil((j + 1) * bin_w).astype(jnp.int32) + x1
                mask = ((ys >= hstart) & (ys < hend) &
                        (xs >= wstart) & (xs < wend))[..., None]
                return jnp.max(jnp.where(mask, fmap, -jnp.inf), axis=(0, 1))

            ii = jnp.arange(ph)
            jj = jnp.arange(pw)
            out = jax.vmap(lambda i: jax.vmap(lambda j: one_bin(i, j))(jj))(ii)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(pool_one)(rois)
