"""Parameter initialization methods.

Reference: BigDL `nn/InitializationMethod.scala:139` — `RandomUniform` (:163,181),
`RandomNormal` (:194), `Zeros` (:206), `Ones`, `ConstInitMethod`, `Xavier` (:257),
`BilinearFiller` (:277), `MsraFiller`; applied through
`nn/abstractnn/Initializable.scala`.

Each initializer is a callable `(rng, shape, fan_in, fan_out, dtype) -> jnp.ndarray`.
Fan computation follows the reference's `VariableFormat` conventions (a Linear weight
of shape (out, in) has fan_in=in; a conv weight (kh, kw, cin, cout) has
fan_in=kh*kw*cin).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Zeros", "Ones", "ConstInitMethod", "RandomUniform", "RandomNormal",
    "Xavier", "MsraFiller", "BilinearFiller", "default_weight_init",
    "default_bias_init", "compute_fans",
]


def compute_fans(shape):
    """fan_in/fan_out for dense (out,in) and conv (kh,kw,cin,cout) shapes."""
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:  # (out, in)
        return shape[1], shape[0]
    receptive = int(np.prod(shape[:-2]))
    return receptive * shape[-2], receptive * shape[-1]


class InitializationMethod:
    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class Ones(InitializationMethod):
    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return jnp.ones(shape, dtype)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class RandomUniform(InitializationMethod):
    """U(lower, upper); with no bounds, Torch's 1/sqrt(fan_in) convention
    (InitializationMethod.scala:163-190)."""

    def __init__(self, lower=None, upper=None):
        self.lower, self.upper = lower, upper

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        if self.lower is None:
            fi, _ = (fan_in, fan_out) if fan_in else compute_fans(shape)
            stdv = 1.0 / float(np.sqrt(fi))
            lo, hi = -stdv, stdv
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, dtype, minval=lo, maxval=hi)


class RandomNormal(InitializationMethod):
    def __init__(self, mean=0.0, stdv=1.0):
        self.mean, self.stdv = mean, stdv

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        return self.mean + self.stdv * jax.random.normal(rng, shape, dtype)


class Xavier(InitializationMethod):
    """U(-a, a), a = sqrt(6/(fan_in+fan_out)) (InitializationMethod.scala:257)."""

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        if fan_in is None:
            fan_in, fan_out = compute_fans(shape)
        a = float(np.sqrt(6.0 / (fan_in + fan_out)))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)


class MsraFiller(InitializationMethod):
    """He/MSRA init: N(0, sqrt(2/fan)) (InitializationMethod.scala MsraFiller)."""

    def __init__(self, variance_norm_average=False):
        self.variance_norm_average = variance_norm_average

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        if fan_in is None:
            fan_in, fan_out = compute_fans(shape)
        n = (fan_in + fan_out) / 2.0 if self.variance_norm_average else fan_in
        std = float(np.sqrt(2.0 / n))
        return std * jax.random.normal(rng, shape, dtype)


class BilinearFiller(InitializationMethod):
    """Bilinear-upsampling kernel (InitializationMethod.scala:277); for
    SpatialFullConvolution weights of shape (kh, kw, cin, cout)."""

    def __call__(self, rng, shape, fan_in=None, fan_out=None, dtype=jnp.float32):
        kh, kw = shape[0], shape[1]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = (kh - 1) / (2.0 * f_h) if kh > 1 else 0.0
        c_w = (kw - 1) / (2.0 * f_w) if kw > 1 else 0.0
        ys = np.arange(kh).reshape(-1, 1)
        xs = np.arange(kw).reshape(1, -1)
        filt = (1 - np.abs(ys / f_h - c_h)) * (1 - np.abs(xs / f_w - c_w))
        w = np.zeros(shape, dtype=np.float32)
        w[..., :, :] = filt[..., None, None]
        return jnp.asarray(w, dtype)


#: Torch default: U(-1/sqrt(fanIn), 1/sqrt(fanIn)) for both weight and bias
default_weight_init = RandomUniform()
default_bias_init = RandomUniform()
