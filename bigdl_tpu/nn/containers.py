"""Composite modules.

Reference: BigDL `nn/Sequential.scala:30` (linear chain), `nn/Concat.scala`
(parallel branches concatenated along a dim), `nn/ConcatTable.scala` (branches
returning a Table), `nn/ParallelTable.scala` (i-th child on i-th input),
`nn/MapTable.scala` (one child mapped over every input), `nn/Identity.scala`,
`nn/Echo.scala`, `nn/Bottle.scala`.

TPU-native notes: containers thread a `training` flag and split the PRNG key per
child; child params/state are list-pytrees, so a whole model is a single pytree that
pjit can shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Container, Module

__all__ = ["Sequential", "Concat", "ConcatTable", "ParallelTable", "MapTable",
           "Identity", "Echo", "Bottle"]


class Sequential(Container):
    """BigDL: nn/Sequential.scala:30 — fold input through children in order."""

    def apply(self, params, state, input, *, training=False, rng=None):
        rngs = self._split_rng(rng)
        new_states = []
        x = input
        for m, p, s, k in zip(self.modules, params, state, rngs):
            x, ns = m.apply(p, s, x, training=training, rng=k)
            new_states.append(ns)
        return x, new_states


class Concat(Container):
    """BigDL: nn/Concat.scala — run children on the same input, concatenate outputs
    along `dimension`.  Reference uses 1-based dims over NCHW; here `dimension` is a
    0-based axis over the canonical NHWC layout (channel axis = -1)."""

    def __init__(self, dimension: int = -1):
        super().__init__()
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        rngs = self._split_rng(rng)
        outs, new_states = [], []
        for m, p, s, k in zip(self.modules, params, state, rngs):
            o, ns = m.apply(p, s, input, training=training, rng=k)
            outs.append(o)
            new_states.append(ns)
        return jnp.concatenate(outs, axis=self.dimension), new_states


class ConcatTable(Container):
    """BigDL: nn/ConcatTable.scala — children on same input, outputs as a list."""

    def apply(self, params, state, input, *, training=False, rng=None):
        rngs = self._split_rng(rng)
        outs, new_states = [], []
        for m, p, s, k in zip(self.modules, params, state, rngs):
            o, ns = m.apply(p, s, input, training=training, rng=k)
            outs.append(o)
            new_states.append(ns)
        return outs, new_states


class ParallelTable(Container):
    """BigDL: nn/ParallelTable.scala — i-th child applied to i-th input element."""

    def apply(self, params, state, input, *, training=False, rng=None):
        rngs = self._split_rng(rng)
        outs, new_states = [], []
        for m, p, s, x, k in zip(self.modules, params, state, input, rngs):
            o, ns = m.apply(p, s, x, training=training, rng=k)
            outs.append(o)
            new_states.append(ns)
        return outs, new_states


class MapTable(Container):
    """BigDL: nn/MapTable.scala — ONE shared child mapped over each input element
    (parameters shared across applications)."""

    def __init__(self, module: Module = None):
        super().__init__()
        if module is not None:
            self.add(module)

    def init(self, rng):
        p, s = self.modules[0].init(rng)
        return [p], [s]

    def apply(self, params, state, input, *, training=False, rng=None):
        m, p, s = self.modules[0], params[0], state[0]
        rngs = ([None] * len(input) if rng is None
                else list(jax.random.split(rng, max(len(input), 1))))
        outs = []
        ns = s
        for x, k in zip(input, rngs):
            o, ns = m.apply(p, ns, x, training=training, rng=k)
            outs.append(o)
        return outs, [ns]


class Identity(Module):
    """BigDL: nn/Identity.scala."""

    def _apply(self, params, input):
        return input


class Echo(Module):
    """BigDL: nn/Echo.scala — identity that prints activation shape (debug aid).
    Uses jax.debug.callback so it also works under jit."""

    def _apply(self, params, input):
        jax.debug.print("{name}: shape {shape}", name=self.name,
                        shape=jnp.asarray(jnp.shape(input)))
        return input


class Bottle(Container):
    """BigDL: nn/Bottle.scala — collapse leading dims, apply child, restore.

    `Bottle(module, n_input_dim=2)` flattens an (d1, d2, ..., features) input to
    (d1*d2*..., features), applies the child, and unflattens.
    """

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: int = None):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim or n_input_dim

    def apply(self, params, state, input, *, training=False, rng=None):
        lead = input.shape[:self.n_input_dim]
        rest = input.shape[self.n_input_dim:]
        flat = input.reshape((-1,) + rest)
        out, ns = self.modules[0].apply(params[0], state[0], flat,
                                        training=training, rng=rng)
        out = out.reshape(lead + out.shape[1:])
        return out, [ns]
