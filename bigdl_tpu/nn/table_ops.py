"""Multi-input (Table) layers.

Reference: one file each under BigDL `nn/`: CAddTable.scala, CSubTable.scala,
CMulTable.scala, CDivTable.scala, CMaxTable.scala, CMinTable.scala,
JoinTable.scala, SplitTable.scala, NarrowTable.scala, FlattenTable.scala,
SelectTable.scala, MixtureTable.scala, Pack.scala.

Inputs/outputs are Python lists (pytrees) — the reference's `Table` Activity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .module import Module

__all__ = ["CAddTable", "CSubTable", "CMulTable", "CDivTable", "CMaxTable",
           "CMinTable", "JoinTable", "SplitTable", "NarrowTable", "FlattenTable",
           "SelectTable", "MixtureTable", "Pack"]


class CAddTable(Module):
    def __init__(self, inplace: bool = False):
        super().__init__()
        # functional arrays have no in-place add; kept for signature parity
        # and wire-format fidelity (interop/bigdl.py echoes it back)
        self.inplace = inplace

    def _apply(self, params, inputs):
        return functools.reduce(jnp.add, inputs)


class CSubTable(Module):
    def _apply(self, params, inputs):
        return inputs[0] - inputs[1]


class CMulTable(Module):
    def _apply(self, params, inputs):
        return functools.reduce(jnp.multiply, inputs)


class CDivTable(Module):
    def _apply(self, params, inputs):
        return inputs[0] / inputs[1]


class CMaxTable(Module):
    def _apply(self, params, inputs):
        return functools.reduce(jnp.maximum, inputs)


class CMinTable(Module):
    def _apply(self, params, inputs):
        return functools.reduce(jnp.minimum, inputs)


class JoinTable(Module):
    """Concatenate table elements along `dimension` (nn/JoinTable.scala).
    0-based axis; `n_input_dims` kept for signature parity."""

    def __init__(self, dimension: int, n_input_dims: int = 0):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def _apply(self, params, inputs):
        return jnp.concatenate(list(inputs), axis=self.dimension)


class SplitTable(Module):
    """Split a tensor into a table along `dimension` (nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = 0):
        super().__init__()
        self.dimension = dimension

    def _apply(self, params, x):
        n = x.shape[self.dimension]
        return [jnp.take(x, i, axis=self.dimension) for i in range(n)]


class NarrowTable(Module):
    """Sub-range of a table (nn/NarrowTable.scala). 0-based offset."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def _apply(self, params, inputs):
        length = self.length
        if length < 0:
            length = len(inputs) - self.offset + length + 1
        return list(inputs)[self.offset:self.offset + length]


class FlattenTable(Module):
    """Flatten nested tables into one flat list (nn/FlattenTable.scala)."""

    def _apply(self, params, inputs):
        out = []

        def rec(t):
            if isinstance(t, (list, tuple)):
                for e in t:
                    rec(e)
            else:
                out.append(t)

        rec(inputs)
        return out


class SelectTable(Module):
    """Pick element `index` of a table (nn/SelectTable.scala). 0-based."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def _apply(self, params, inputs):
        return inputs[self.index]


class MixtureTable(Module):
    """Mixture-of-experts blend (nn/MixtureTable.scala): input =
    [gate (batch, n), experts: list of n (batch, ...) or tensor (batch, n, ...)];
    output = sum_i gate_i * expert_i."""

    def __init__(self, dim: int = None):
        super().__init__()
        self.dim = dim

    def _apply(self, params, inputs):
        gate, experts = inputs[0], inputs[1]
        if isinstance(experts, (list, tuple)):
            experts = jnp.stack(list(experts), axis=1)  # (batch, n, ...)
        g = gate.reshape(gate.shape + (1,) * (experts.ndim - gate.ndim))
        return jnp.sum(g * experts, axis=1)


class Pack(Module):
    """Stack table elements along a new `dimension` (nn/Pack.scala). 0-based."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def _apply(self, params, inputs):
        if isinstance(inputs, (list, tuple)):
            return jnp.stack(list(inputs), axis=self.dimension)
        return jnp.expand_dims(inputs, self.dimension)
