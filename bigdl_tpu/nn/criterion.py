"""Loss functions (criterions).

Reference: BigDL's 26 criterions, one file each under `nn/` (SURVEY.md §2.3):
AbsCriterion, BCECriterion, ClassNLLCriterion, ClassSimplexCriterion,
CosineDistanceCriterion, CosineEmbeddingCriterion, CrossEntropyCriterion,
DiceCoefficientCriterion, DistKLDivCriterion, HingeEmbeddingCriterion, L1Cost,
L1HingeEmbeddingCriterion, L1Penalty, MarginCriterion, MarginRankingCriterion,
MSECriterion, MultiCriterion, MultiLabelMarginCriterion,
MultiLabelSoftMarginCriterion, MultiMarginCriterion, ParallelCriterion,
SmoothL1Criterion, SmoothL1CriterionWithWeights, SoftMarginCriterion,
SoftmaxWithCriterion, TimeDistributedCriterion.

TPU-native notes: each criterion's core is a pure `loss(output, target)` scalar
function; `backward` is `jax.grad` of it (the reference hand-writes every
updateGradInput).  `size_average=True` (the Torch default) mean-reduces over the
batch.  Class labels are 0-based int arrays (reference uses 1-based Torch floats;
pass `one_based=True` where offered for data parity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import Criterion

__all__ = [
    "AbsCriterion", "BCECriterion", "ClassNLLCriterion", "ClassSimplexCriterion",
    "CosineDistanceCriterion", "CosineEmbeddingCriterion", "CrossEntropyCriterion",
    "DiceCoefficientCriterion", "DistKLDivCriterion", "HingeEmbeddingCriterion",
    "L1Cost", "L1HingeEmbeddingCriterion", "L1Penalty", "MarginCriterion",
    "MarginRankingCriterion", "MSECriterion", "MultiCriterion",
    "MultiLabelMarginCriterion", "MultiLabelSoftMarginCriterion",
    "MultiMarginCriterion", "ParallelCriterion", "SmoothL1Criterion",
    "SmoothL1CriterionWithWeights", "SoftMarginCriterion", "SoftmaxWithCriterion",
    "TimeDistributedCriterion",
]


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


class AbsCriterion(Criterion):
    """mean |x - y| (nn/AbsCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jnp.abs(output - target), self.size_average)


class MSECriterion(Criterion):
    """mean (x - y)^2 (nn/MSECriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jnp.square(output - target), self.size_average)


class BCECriterion(Criterion):
    """Binary cross-entropy on probabilities, optional per-element weights
    (nn/BCECriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = weights
        self.size_average = size_average

    def loss(self, output, target):
        eps = 1e-12
        o = jnp.clip(output, eps, 1.0 - eps)
        l = -(target * jnp.log(o) + (1.0 - target) * jnp.log1p(-o))
        if self.weights is not None:
            l = l * self.weights
        return _reduce(l, self.size_average)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probabilities (nn/ClassNLLCriterion.scala).
    Expects LogSoftMax output (batch, classes) and integer labels (batch,).
    Optional per-class `weights`; mean is weight-normalized like the reference.

    Labels are 0-based by default (idiomatic JAX); pass ``one_based=True`` for
    BigDL/Torch-style 1-based labels.  Negative labels are treated as padding
    and excluded from the loss (the standard ignore-index; the reference's
    1-based labels made 0 the natural pad sentinel — 0-based labels need an
    explicit one).  An out-of-range-high label yields NaN loss (JAX gathers
    fill out-of-bounds with NaN) — the reference instead threw
    `curTarget >= 1 && curTarget <= nClasses`; watch the logged loss.

    `label_smoothing=eps` (net-new vs the reference) mixes the one-hot
    target with the uniform distribution: loss = (1-eps)*NLL(target) +
    eps*mean over classes of -log p — the standard regularizer for
    large-vocab/ImageNet training.  Incompatible with per-class weights."""

    def __init__(self, weights=None, size_average: bool = True,
                 one_based: bool = False, label_smoothing: float = 0.0):
        super().__init__()
        self.weights = weights
        self.size_average = size_average
        self.one_based = one_based
        if not 0.0 <= label_smoothing < 1.0:
            raise ValueError(f"label_smoothing {label_smoothing}")
        if label_smoothing and weights is not None:
            raise ValueError("label_smoothing with per-class weights is "
                             "not supported")
        self.label_smoothing = label_smoothing

    def loss(self, output, target):
        t = target.astype(jnp.int32).reshape(-1)
        if self.one_based:
            t = t - 1
        valid = t >= 0
        picked = jnp.take_along_axis(output, jnp.maximum(t, 0)[:, None],
                                     axis=1)[:, 0]
        if self.label_smoothing:
            eps = self.label_smoothing
            uniform = -jnp.mean(output, axis=-1)  # -E_uniform[log p]
            smoothed = jnp.where(valid,
                                 (1 - eps) * (-picked) + eps * uniform, 0.0)
            if self.size_average:
                return jnp.sum(smoothed) / jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(smoothed)
        if self.weights is not None:
            w = jnp.take(self.weights, jnp.maximum(t, 0)) * valid
            total = -jnp.sum(w * picked)
            return (total / jnp.maximum(jnp.sum(w), 1e-12)
                    if self.size_average else total)
        masked = jnp.where(valid, -picked, 0.0)
        if self.size_average:
            return jnp.sum(masked) / jnp.maximum(jnp.sum(valid), 1)
        return jnp.sum(masked)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (nn/CrossEntropyCriterion.scala). Expects raw
    logits."""

    def __init__(self, weights=None, size_average: bool = True,
                 one_based: bool = False, label_smoothing: float = 0.0):
        super().__init__()
        self._nll = ClassNLLCriterion(weights, size_average, one_based,
                                      label_smoothing)

    def loss(self, output, target):
        return self._nll.loss(jax.nn.log_softmax(output, axis=-1), target)


class ClassSimplexCriterion(Criterion):
    """MSE against a regular-simplex embedding of the labels
    (nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes: int, size_average: bool = True,
                 one_based: bool = False):
        super().__init__()
        self.n_classes = n_classes
        self.size_average = size_average
        self.one_based = one_based
        self.simplex = self._build_simplex(n_classes)

    @staticmethod
    def _build_simplex(n):
        # unit-norm regular-simplex vertices: centered identity, row-normalized
        import numpy as np
        eye = np.eye(n, dtype=np.float32)
        centered = eye - eye.mean(axis=0, keepdims=True)
        norms = np.linalg.norm(centered, axis=1, keepdims=True)
        return jnp.asarray(centered / norms)

    def loss(self, output, target):
        t = target.astype(jnp.int32).reshape(-1)
        if self.one_based:
            t = t - 1
        goal = jnp.take(self.simplex, t, axis=0)
        return _reduce(jnp.square(output - goal), self.size_average)


class CosineDistanceCriterion(Criterion):
    """1 - cos(x, y) per row (nn/CosineDistanceCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        o = output / (jnp.linalg.norm(output, axis=-1, keepdims=True) + 1e-12)
        t = target / (jnp.linalg.norm(target, axis=-1, keepdims=True) + 1e-12)
        return _reduce(1.0 - jnp.sum(o * t, axis=-1), self.size_average)


class CosineEmbeddingCriterion(Criterion):
    """Input [x1, x2], target ±1 (nn/CosineEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        x1, x2 = output[0], output[1]
        cos = (jnp.sum(x1 * x2, axis=-1) /
               (jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1) + 1e-12))
        t = jnp.reshape(target, cos.shape)
        l = jnp.where(t > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(l, self.size_average)


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap (nn/DiceCoefficientCriterion.scala)."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.size_average = size_average
        self.epsilon = epsilon

    def loss(self, output, target):
        o = output.reshape(output.shape[0], -1)
        t = target.reshape(target.shape[0], -1)
        inter = jnp.sum(o * t, axis=1)
        denom = jnp.sum(o, axis=1) + jnp.sum(t, axis=1)
        dice = (2.0 * inter + self.epsilon) / (denom + self.epsilon)
        return _reduce(1.0 - dice, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL(target || exp(output)): target * (log(target) - output)
    (nn/DistKLDivCriterion.scala; output is log-prob)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        l = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12))
                                            - output), 0.0)
        if self.size_average:
            return jnp.sum(l) / output.shape[0]
        return jnp.sum(l)


class HingeEmbeddingCriterion(Criterion):
    """x if y==1 else max(0, margin - x) (nn/HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        l = jnp.where(target > 0, output,
                      jnp.maximum(0.0, self.margin - output))
        return _reduce(l, self.size_average)


class L1Cost(Criterion):
    """sum |x| (nn/L1Cost.scala); target ignored."""

    def loss(self, output, target=None):
        return jnp.sum(jnp.abs(output))


class L1HingeEmbeddingCriterion(Criterion):
    """L1 distance hinge on pairs [x1, x2], target ±1
    (nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def loss(self, output, target):
        d = jnp.sum(jnp.abs(output[0] - output[1]), axis=-1)
        t = jnp.reshape(target, d.shape)
        l = jnp.where(t > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.mean(l)


class L1Penalty(Criterion):
    """L1 activation penalty pass-through (nn/L1Penalty.scala). As a criterion:
    l1weight * sum|x|."""

    def __init__(self, l1weight: float = 1.0, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = l1weight
        self.size_average = size_average

    def loss(self, output, target=None):
        return self.l1weight * _reduce(jnp.abs(output), self.size_average)


class MarginCriterion(Criterion):
    """Hinge: max(0, margin - y*x) (nn/MarginCriterion.scala); squared variant
    gives L2-SVM."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        super().__init__()
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def loss(self, output, target):
        l = jnp.maximum(0.0, self.margin - target * output)
        if self.squared:
            l = jnp.square(l)
        return _reduce(l, self.size_average)


class MarginRankingCriterion(Criterion):
    """max(0, -y*(x1-x2) + margin) on input [x1, x2]
    (nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def loss(self, output, target):
        d = output[0] - output[1]
        t = jnp.reshape(target, d.shape) if hasattr(target, "shape") else target
        return _reduce(jnp.maximum(0.0, -t * d + self.margin), self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the SAME (output, target)
    (nn/MultiCriterion.scala)."""

    def __init__(self):
        super().__init__()
        self.criterions = []
        self.weights = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, output, target):
        return sum(w * c.loss(output, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """Weighted sum of criterions, i-th criterion on i-th (output, target) pair
    (nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.criterions = []
        self.weights = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def loss(self, output, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.loss(output[i], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """Multi-class multi-label hinge (nn/MultiLabelMarginCriterion.scala).
    Target: (batch, n) 0-based label indices padded with -1 (reference pads with
    0 in 1-based space)."""

    def __init__(self, size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.size_average = size_average
        self.one_based = one_based

    def loss(self, output, target):
        t = target.astype(jnp.int32)
        if self.one_based:
            t = t - 1  # padding 0 -> -1
        n = output.shape[-1]
        valid = t >= 0
        t_safe = jnp.maximum(t, 0)
        is_target = jnp.zeros_like(output, dtype=bool)
        batch_idx = jnp.arange(output.shape[0])[:, None]
        # .max, not .set: padding slots all scatter to index 0 and a False
        # write must not clobber a genuine class-0 True (duplicate-index
        # scatter order is unspecified)
        is_target = is_target.at[batch_idx, t_safe].max(valid)
        tgt_scores = jnp.take_along_axis(output, t_safe, axis=1)  # (b, n)
        # hinge of every non-target against every valid target
        margins = 1.0 - tgt_scores[:, :, None] + output[:, None, :]  # (b, tgt, cls)
        mask = valid[:, :, None] & (~is_target[:, None, :])
        l = jnp.sum(jnp.where(mask, jnp.maximum(0.0, margins), 0.0), axis=(1, 2)) / n
        return _reduce(l, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid + BCE per class (nn/MultiLabelSoftMarginCriterion.scala); expects
    raw scores."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = weights
        self.size_average = size_average

    def loss(self, output, target):
        l = (jax.nn.softplus(-output) * target
             + jax.nn.softplus(output) * (1.0 - target))
        if self.weights is not None:
            l = l * self.weights
        l = jnp.mean(l, axis=-1)
        return _reduce(l, self.size_average)


class MultiMarginCriterion(Criterion):
    """Multi-class hinge (nn/MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.p, self.weights, self.margin = p, weights, margin
        self.size_average = size_average
        self.one_based = one_based

    def loss(self, output, target):
        t = target.astype(jnp.int32).reshape(-1)
        if self.one_based:
            t = t - 1
        n = output.shape[-1]
        tgt = jnp.take_along_axis(output, t[:, None], axis=1)
        m = jnp.maximum(0.0, self.margin - tgt + output) ** self.p
        if self.weights is not None:
            m = m * jnp.take(self.weights, t)[:, None]
        onehot = jax.nn.one_hot(t, n, dtype=bool)
        l = jnp.sum(jnp.where(onehot, 0.0, m), axis=-1) / n
        return _reduce(l, self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber with delta=1 (nn/SmoothL1Criterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        d = jnp.abs(output - target)
        l = jnp.where(d < 1.0, 0.5 * jnp.square(d), d - 0.5)
        return _reduce(l, self.size_average)


class SmoothL1CriterionWithWeights(Criterion):
    """Smooth-L1 with inside/outside weights and sigma, as used by Fast-RCNN
    (nn/SmoothL1CriterionWithWeights.scala). Target is a table
    [t, inside_w, outside_w] (weights optional)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def loss(self, output, target):
        if isinstance(target, (list, tuple)):
            t = target[0]
            in_w = target[1] if len(target) > 1 else 1.0
            out_w = target[2] if len(target) > 2 else 1.0
        else:
            t, in_w, out_w = target, 1.0, 1.0
        d = in_w * (output - t)
        ad = jnp.abs(d)
        l = jnp.where(ad < 1.0 / self.sigma2,
                      0.5 * self.sigma2 * jnp.square(d),
                      ad - 0.5 / self.sigma2)
        l = out_w * l
        total = jnp.sum(l)
        return total / self.num if self.num > 0 else total


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) (nn/SoftMarginCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def loss(self, output, target):
        return _reduce(jax.nn.softplus(-target * output), self.size_average)


class SoftmaxWithCriterion(Criterion):
    """Caffe-style SoftmaxWithLoss over NHWC spatial maps
    (nn/SoftmaxWithCriterion.scala): per-pixel cross-entropy with optional
    ignore_label; normalize_mode in {'valid','batch_size','full','none'}."""

    def __init__(self, ignore_label: int = None, normalize_mode: str = "valid",
                 one_based: bool = False):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode
        self.one_based = one_based

    def loss(self, output, target):
        logp = jax.nn.log_softmax(output, axis=-1)
        t = target.astype(jnp.int32)
        if self.one_based:
            t = t - 1
        valid = jnp.ones_like(t, dtype=bool) if self.ignore_label is None \
            else t != (self.ignore_label - (1 if self.one_based else 0))
        t_safe = jnp.where(valid, t, 0)
        picked = jnp.take_along_axis(logp, t_safe[..., None], axis=-1)[..., 0]
        total = -jnp.sum(jnp.where(valid, picked, 0.0))
        if self.normalize_mode == "valid":
            return total / jnp.maximum(jnp.sum(valid), 1)
        if self.normalize_mode == "batch_size":
            return total / output.shape[0]
        if self.normalize_mode == "full":
            return total / t.size
        return total


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every time step of (batch, time, ...) output
    (nn/TimeDistributedCriterion.scala)."""

    def __init__(self, critrn: Criterion, size_average: bool = False):
        super().__init__()
        self.critrn = critrn
        self.size_average = size_average

    def loss(self, output, target):
        T = output.shape[1]
        # lax.scan, not a Python loop: the body traces ONCE, so a T=512 LM
        # criterion does not unroll 512 slice+gather+mean subgraphs (plus
        # their VJPs) into the compiled train step.  A flattened single
        # call would be cheaper still but changes semantics when padding
        # varies per timestep (per-step means vs one global mean) — the
        # reference applies the criterion per step (TimeDistributed
        # Criterion.scala), so scan preserves that exactly.
        o_t = jnp.moveaxis(output, 1, 0)
        t_t = jnp.moveaxis(target, 1, 0)

        def body(acc, ot):
            o, t = ot
            return acc + self.critrn.loss(o, t), None

        # carry dtype follows the inner loss (f64 under jax_enable_x64,
        # custom criterions) — a pinned f32 carry would make scan reject
        # the promoted acc + loss
        loss_aval = jax.eval_shape(
            self.critrn.loss,
            jax.ShapeDtypeStruct(o_t.shape[1:], o_t.dtype),
            jax.ShapeDtypeStruct(t_t.shape[1:], t_t.dtype))
        total, _ = jax.lax.scan(body, jnp.zeros((), loss_aval.dtype),
                                (o_t, t_t))
        return total / T if self.size_average else total
