"""Detection ops: non-maximum suppression.

Reference: nn/Nms.scala — greedy NMS over scored boxes used beside
`RoiPooling` in the Fast-R-CNN path.

TPU-native re-design: the reference's data-dependent while-loop over
surviving boxes becomes a fixed-trip `lax.fori_loop` (static shapes, jit- and
vmap-safe): each iteration selects the highest-scoring live box, emits it,
and suppresses boxes with IoU above threshold.  Suppressed slots are filled
with -1, so the output is a static (max_output,) index array.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .module import Module

__all__ = ["Nms", "nms"]


def _iou_matrix(boxes):
    """(n, 4) xyxy boxes -> (n, n) IoU."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, scores, iou_threshold: float = 0.5,
        max_output: int | None = None, score_threshold: float = -jnp.inf):
    """Greedy NMS.  Returns (indices, num_valid): `indices` is a static
    (max_output,) int32 array padded with -1."""
    n = boxes.shape[0]
    if max_output is None:
        max_output = n
    iou = _iou_matrix(boxes)
    live = scores > score_threshold

    def body(_, carry):
        sel, count, live = carry
        best = jnp.argmax(jnp.where(live, scores, -jnp.inf))
        any_live = jnp.any(live)
        sel = sel.at[count].set(jnp.where(any_live, best, -1))
        count = count + any_live.astype(jnp.int32)
        # kill the selected box and everything overlapping it
        suppress = iou[best] > iou_threshold
        live = live & ~suppress & (jnp.arange(n) != best)
        live = live & any_live  # freeze once exhausted
        return sel, count, live

    sel0 = jnp.full((max_output,), -1, dtype=jnp.int32)
    sel, count, _ = lax.fori_loop(0, max_output, body,
                                  (sel0, jnp.int32(0), live))
    return sel, count


class Nms(Module):
    """Module wrapper: input is a dict/tuple (boxes (n,4), scores (n,));
    output is the padded index array (reference Nms.scala mutates an output
    buffer of indices)."""

    def __init__(self, iou_threshold: float = 0.5,
                 max_output: int | None = None):
        super().__init__()
        self.iou_threshold = iou_threshold
        self.max_output = max_output

    def _apply(self, params, inp):
        boxes, scores = inp
        idx, _ = nms(boxes, scores, self.iou_threshold, self.max_output)
        return idx
