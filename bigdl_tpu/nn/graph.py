"""Graph container: arbitrary-DAG models.

Reference: BigDL `nn/Graph.scala:58` — a module built from `ModuleNode`s, executed
in topological order (:64-120) over `utils/DirectedGraph.scala`; `Input`
placeholder nodes (nn/Input.scala, created via Graph.scala:320).

Usage (mirrors the reference's functional-graph API):

    inp = Input()
    h = Linear(10, 20)(inp)
    a = ReLU()(h)
    b = Tanh()(h)
    out = CAddTable()([a, b])
    model = Graph(inp, out)

TPU-native notes: execution order is resolved at trace time (host Python), so the
whole DAG flattens into one XLA program — the topo sort has zero runtime cost.
"""

from __future__ import annotations

from typing import List, Sequence, Union

import jax

from ..utils.graph import DirectedGraph, Node
from .module import Module

__all__ = ["ModuleNode", "Input", "Graph"]


class ModuleNode(Node):
    """A Node whose element is a Module; calling a Module on node(s) builds edges
    (reference: the implicit `inputs` API of nn/Graph.scala)."""

    def __init__(self, module: Module):
        super().__init__(module)


class _InputModule(Module):
    def _apply(self, params, x):
        return x


def Input() -> ModuleNode:
    """Placeholder input node (reference: nn/Input.scala)."""
    return ModuleNode(_InputModule())


def _node(module: Module, inputs) -> ModuleNode:
    n = ModuleNode(module)
    if inputs is None:
        return n
    if isinstance(inputs, (list, tuple)):
        for i in inputs:
            i.point_to(n)
    else:
        inputs.point_to(n)
    return n


# make every Module callable on nodes: module(node) -> node
_orig_call = Module.__call__


def _module_call(self, *args, **kwargs):
    if len(args) == 1 and isinstance(args[0], ModuleNode):
        return _node(self, args[0])
    if (len(args) == 1 and isinstance(args[0], (list, tuple)) and args[0]
            and all(isinstance(a, ModuleNode) for a in args[0])):
        return _node(self, args[0])
    return _orig_call(self, *args, **kwargs)


Module.__call__ = _module_call


class Graph(Module):
    """DAG container (reference: nn/Graph.scala:58)."""

    def __init__(self, inputs: Union[ModuleNode, Sequence[ModuleNode]],
                 outputs: Union[ModuleNode, Sequence[ModuleNode]]):
        super().__init__()
        self.input_nodes: List[ModuleNode] = (
            list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
        self.output_nodes: List[ModuleNode] = (
            list(outputs) if isinstance(outputs, (list, tuple)) else [outputs])
        # topo order over the union of everything reachable from the inputs
        virtual_src = Node(None)
        for i in self.input_nodes:
            virtual_src.point_to(i)
        order = DirectedGraph(virtual_src).topology_sort()
        self.exec_order: List[ModuleNode] = [n for n in order
                                             if n is not virtual_src]
        # detach the virtual source again
        for i in self.input_nodes:
            i.prev_nodes.remove(virtual_src)
        self.modules = [n.element for n in self.exec_order]

    def init(self, rng):
        keys = jax.random.split(rng, max(len(self.modules), 1))
        ps, ss = [], []
        for m, k in zip(self.modules, keys):
            p, s = m.init(k)
            ps.append(p)
            ss.append(s)
        return ps, ss

    def apply(self, params, state, input, *, training=False, rng=None):
        rngs = ([None] * len(self.exec_order) if rng is None
                else list(jax.random.split(rng, max(len(self.exec_order), 1))))
        values = {}
        inputs_list = (input if isinstance(input, (list, tuple))
                       else [input])
        for inp_node, x in zip(self.input_nodes, inputs_list):
            values[id(inp_node)] = x

        new_states = []
        for n, p, s, k in zip(self.exec_order, params, state, rngs):
            if id(n) in values:  # an Input node
                new_states.append(s)
                continue
            preds = n.prev_nodes
            if len(preds) == 1:
                x = values[id(preds[0])]
            else:
                x = [values[id(pn)] for pn in preds]
            y, ns = n.element.apply(p, s, x, training=training, rng=k)
            values[id(n)] = y
            new_states.append(ns)

        outs = [values[id(o)] for o in self.output_nodes]
        return (outs[0] if len(outs) == 1 else outs), new_states
