"""Embedding layers.

Reference: BigDL `nn/LookupTable.scala` (embedding with optional max-norm
renorm).  Lived in nn/dropout.py through PR 19; moved here because the
recommendation workload (models/widedeep.py) makes embeddings a
first-class model family rather than a dropout-file tenant.  nn/dropout
keeps a re-export, so existing imports AND bigdl-format save/load (keyed
by class NAME, interop/bigdl.py) are unchanged.

TPU-native notes: LookupTable is a gather (one-hot matmul is left to
XLA's discretion).  The weight carries the ``embedding_row`` role
(parallel/layout.ROLES), so under a MeshLayout the vocab axis shards
jointly over fsdp x tp (and expert where it divides) — each device holds
exactly 1/N of the table and the forward lowers to a local gather, never
a full-table materialization (tools/perf_gate.py `embed.*` rows pin
this).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common import get_policy
from .module import Module

__all__ = ["LookupTable"]


class LookupTable(Module):
    """Embedding lookup (nn/LookupTable.scala): indices -> rows of a
    (n_index, n_output) weight.  Indices are 0-based (reference is 1-based Torch;
    pass `one_based=True` for parity with reference data)."""

    #: rows shard over fsdp x tp (the wide-embedding role, SNIPPETS.md [2])
    PARAM_ROLES = {"weight": "embedding_row"}

    def __init__(self, n_index: int, n_output: int, padding_value: float = None,
                 max_norm: float = None, norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False, one_based: bool = False,
                 w_regularizer=None):
        super().__init__()
        self.n_index, self.n_output = n_index, n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.one_based = one_based
        self.w_regularizer = w_regularizer

    def _init(self, rng):
        w = jax.random.normal(rng, (self.n_index, self.n_output),
                              get_policy().param_dtype)
        if self.padding_value is not None:
            pad_idx = int(self.padding_value) - (1 if self.one_based else 0)
            if 0 <= pad_idx < self.n_index:
                w = w.at[pad_idx].set(0.0)
        return {"weight": w}

    def _apply(self, params, idx):
        w = params["weight"]
        if self.max_norm is not None:
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1, keepdims=True)
            w = jnp.where(norms > self.max_norm, w * (self.max_norm / norms), w)
        i = idx.astype(jnp.int32)
        if self.one_based:
            i = i - 1
        return jnp.take(w, i, axis=0)
