"""bigdl_tpu.nn — the Torch-style layer library, rebuilt TPU-native.

Reference inventory: BigDL `nn/` (151 files, 26,212 LoC — SURVEY.md §2.3).
"""

from .module import Module, Container, Criterion
from .initialization import (Zeros, Ones, ConstInitMethod, RandomUniform,
                             RandomNormal, Xavier, MsraFiller, BilinearFiller)
from .containers import (Sequential, Concat, ConcatTable, ParallelTable,
                         MapTable, Identity, Echo, Bottle)
from .graph import Graph, Input, ModuleNode
from .activation import (ReLU, ReLU6, PReLU, RReLU, LeakyReLU, ELU, GELU,
                         Tanh, TanhShrink, Sigmoid, SoftMax, SoftMin,
                         SoftPlus, SoftSign, SoftShrink, HardShrink, HardTanh,
                         Threshold, LogSoftMax, LogSigmoid)
from .linear import (Linear, Bilinear, CMul, CAdd, Mul, Add, MulConstant,
                     AddConstant)
from .conv import (SpatialConvolution, SpatialDilatedConvolution,
                   SpatialFullConvolution, TemporalConvolution,
                   VolumetricConvolution, SpatialShareConvolution,
                   SpatialConvolutionMap)
from .pooling import (SpatialMaxPooling, SpatialAveragePooling,
                      VolumetricMaxPooling, RoiPooling)
from .detection import Nms
from .tree import TreeLSTM, BinaryTreeLSTM
from .normalization import (BatchNormalization, SpatialBatchNormalization,
                            LayerNorm, Normalize, SpatialCrossMapLRN,
                            SpatialWithinChannelLRN,
                            SpatialSubtractiveNormalization,
                            SpatialDivisiveNormalization,
                            SpatialContrastiveNormalization)
from .dropout import Dropout, GradientReversal
from .embedding import LookupTable
from .shape import (Reshape, InferReshape, View, Transpose, Replicate, Squeeze,
                    Unsqueeze, Select, Narrow, Index, MaskedSelect, Reverse,
                    Padding, SpatialZeroPadding, Contiguous)
from .math_ops import (Power, Sqrt, Square, Clamp, Max, Min, Mean, Sum, Exp,
                       Log, Abs, Scale, MM, MV, Cosine, Euclidean, DotProduct,
                       PairwiseDistance, CosineDistance)
from .table_ops import (CAddTable, CSubTable, CMulTable, CDivTable, CMaxTable,
                        CMinTable, JoinTable, SplitTable, NarrowTable,
                        FlattenTable, SelectTable, MixtureTable, Pack)
from .recurrent import (Cell, RnnCell, LSTM, LSTMPeephole, GRU,
                        ConvLSTMPeephole, ConvLSTMPeephole3D, Recurrent,
                        TimeDistributed,
                        BiRecurrent)
from .criterion import (
    AbsCriterion, BCECriterion, ClassNLLCriterion, ClassSimplexCriterion,
    CosineDistanceCriterion, CosineEmbeddingCriterion, CrossEntropyCriterion,
    DiceCoefficientCriterion, DistKLDivCriterion, HingeEmbeddingCriterion,
    L1Cost, L1HingeEmbeddingCriterion, L1Penalty, MarginCriterion,
    MarginRankingCriterion, MSECriterion, MultiCriterion,
    MultiLabelMarginCriterion, MultiLabelSoftMarginCriterion,
    MultiMarginCriterion, ParallelCriterion, SmoothL1Criterion,
    SmoothL1CriterionWithWeights, SoftMarginCriterion, SoftmaxWithCriterion,
    TimeDistributedCriterion)
from .attention import MultiHeadAttention
from .fused import ConvBN, ConvBNAddReLU, fuse_conv_bn
