"""Attention layers (net-new vs the 2017 reference; required for the rebuild's
long-context capability, SURVEY.md §5.7/§7).

MultiHeadAttention: fused qkv projection -> flash attention (Pallas kernel on
TPU, ops/attention.py) -> output projection.  With `seq_parallel=True` the
attention core runs as a ring over the mesh 'seq' axis (parallel/ring_attention)
so sequences sharded across devices never gather.  `BIGDL_TPU_RING_ATTN=1`
instead reuses a MeshLayout's 'tp' axis as the sequence axis: on a tp>1
mesh whose sequence length divides |tp|, the attention core rings over
'tp' — long contexts shard across the tensor-parallel group with no extra
mesh axis (parity-pinned on the CPU mesh, tests/test_pipeline_expert.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..common import get_policy
from .initialization import compute_fans, default_weight_init
from .module import Module

__all__ = ["MultiHeadAttention"]


class MultiHeadAttention(Module):
    """Self-attention over [B, T, E] inputs."""

    #: (E, E) projections are applied x @ w (in-major): kernel_in
    PARAM_ROLES = {"wq": "kernel_in", "wk": "kernel_in", "wv": "kernel_in",
                   "wo": "kernel_in", "*": "bias"}

    def __init__(self, embed_dim: int, num_heads: int, causal: bool = False,
                 seq_parallel: bool = False, seq_axis: str = "seq",
                 with_bias: bool = True):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"embed_dim {embed_dim} % num_heads {num_heads}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.causal = causal
        self.seq_parallel = seq_parallel
        self.seq_axis = seq_axis
        self.with_bias = with_bias

    def _init(self, rng):
        ks = jax.random.split(rng, 4)
        e = self.embed_dim
        winit = self.weight_initializer or default_weight_init
        dt = get_policy().param_dtype

        def w(k, shape):
            fi, fo = compute_fans(shape)
            return winit(k, shape, fi, fo, dt)

        p = {"wq": w(ks[0], (e, e)), "wk": w(ks[1], (e, e)),
             "wv": w(ks[2], (e, e)), "wo": w(ks[3], (e, e))}
        if self.with_bias:
            # distinct arrays per bias: aliased leaves crash buffer donation
            # in the compiled train step ("donate the same buffer twice")
            p.update({k: jnp.zeros((e,), dt)
                      for k in ("bq", "bk", "bv", "bo")})
        return p

    def _proj(self, params, x, name):
        c = get_policy().compute_dtype
        y = jax.lax.dot_general(
            x.astype(c), params["w" + name].astype(c),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(c)
        if self.with_bias:
            y = y + params["b" + name].astype(c)
        return y

    def _ring_over_tp(self, T):
        """The env-gated ring-attention seam: a MeshLayout 'tp' axis
        doubles as the sequence axis when BIGDL_TPU_RING_ATTN is set and
        the sequence divides it (parallel/ring_attention)."""
        from ..utils import config
        if not config.get_bool("RING_ATTN", False):
            return None
        from ..parallel.pipeline import _active_mesh
        mesh = _active_mesh()
        if mesh is None or "tp" not in mesh.axis_names:
            return None
        n = int(mesh.shape["tp"])
        if n <= 1 or T % n:
            return None
        return mesh

    def _apply(self, params, x):
        B, T, E = x.shape
        H, D = self.num_heads, self.head_dim
        split = lambda y: y.reshape(B, T, H, D).transpose(0, 2, 1, 3)
        q, k, v = (split(self._proj(params, x, n)) for n in "qkv")
        ring_mesh = None if self.seq_parallel else self._ring_over_tp(T)
        if self.seq_parallel:
            from ..parallel.ring_attention import ring_attention
            o = ring_attention(q, k, v, seq_axis=self.seq_axis,
                               causal=self.causal)
        elif ring_mesh is not None:
            from ..parallel.ring_attention import ring_attention
            o = ring_attention(q, k, v, mesh=ring_mesh, seq_axis="tp",
                               causal=self.causal,
                               batch_axis=("data", "fsdp"))
        else:
            from ..ops.attention import flash_attention
            o = flash_attention(q, k, v, causal=self.causal)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, E)
        return self._proj(params, o, "o")
