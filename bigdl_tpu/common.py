"""Global configuration: dtype policy, default RNG stream, small helpers.

TPU-native re-design notes
--------------------------
The reference (BigDL, /root/reference) threads a `TensorNumeric[T]` typeclass through
every op so the same layer code runs at Float or Double precision
(tensor/TensorNumeric.scala:21).  On TPU the analogous global knob is the *dtype
policy*: parameters are kept in `param_dtype` (float32 by default) while compute and
the gradient wire format may be bfloat16 — mirroring BigDL's bf16-truncated gradient
wire format (parameters/FP16CompressedTensor.scala:271-279, which keeps the top 16
bits of an IEEE float32, i.e. exactly bfloat16).
"""

from __future__ import annotations

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DTypePolicy",
    "get_policy",
    "set_policy",
    "get_default_rng",
    "set_seed",
    "next_rng_key",
]


class DTypePolicy:
    """Dtype policy: param storage dtype, compute dtype, and wire (collective) dtype."""

    def __init__(self, param_dtype=jnp.float32, compute_dtype=jnp.float32,
                 wire_dtype=jnp.bfloat16):
        self.param_dtype = param_dtype
        self.compute_dtype = compute_dtype
        # Gradients cross chips in this dtype (bf16 == BigDL's "FP16" truncation wire
        # format, parameters/FP16CompressedTensor.scala:271-279).
        self.wire_dtype = wire_dtype

    def __repr__(self):
        return (f"DTypePolicy(param={jnp.dtype(self.param_dtype).name}, "
                f"compute={jnp.dtype(self.compute_dtype).name}, "
                f"wire={jnp.dtype(self.wire_dtype).name})")


_policy = DTypePolicy()


def get_policy() -> DTypePolicy:
    return _policy


def set_policy(policy: DTypePolicy) -> None:
    global _policy
    _policy = policy


_warned_accum = set()


def conv_accum_dtype():
    """`preferred_element_type` for convolutions under the current policy.

    Explicit f32 accumulation is requested only when computing in f32: jax's
    conv transpose (autodiff) rejects a preferred_element_type that differs
    from the operand dtype (unlike dot_general).  bf16 needs no request —
    the TPU MXU accumulates bf16 convolutions in f32 natively.  Other
    reduced dtypes (e.g. float16, which TPUs do not support natively) get
    same-dtype accumulation and a one-time warning."""
    c = jnp.dtype(_policy.compute_dtype)
    if c == jnp.dtype(jnp.float32):
        return jnp.float32
    if c != jnp.dtype(jnp.bfloat16) and c.name not in _warned_accum:
        _warned_accum.add(c.name)
        import logging
        logging.getLogger("bigdl_tpu").warning(
            "compute_dtype %s: convolutions accumulate in the same dtype "
            "(no f32 accumulation guarantee; prefer bfloat16 on TPU)", c.name)
    return None


class _RngStream:
    """Host-side deterministic key stream (the facade's hidden RNG).

    Plays the role of BigDL's thread-local RandomGenerator singleton
    (utils/RandomGenerator.scala:23-35), re-designed as an explicit splittable
    JAX PRNG key stream.
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = seed
        # key creation is LAZY: jax.random.key initializes the backend, and
        # importing the library must not touch devices (a hung/remote TPU
        # would block every `import bigdl_tpu`)
        self._key = None

    def reset(self, seed: int):
        with self._lock:
            self._seed = seed
            self._key = jax.random.key(seed)

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        """Serializable snapshot of the stream position (for
        checkpoint/resume: restoring replays the exact same key
        sequence)."""
        with self._lock:
            key_data = (None if self._key is None
                        else np.asarray(jax.random.key_data(self._key)))
            return {"seed": self._seed, "key_data": key_data}

    def set_state(self, state) -> None:
        with self._lock:
            self._seed = int(state["seed"])
            kd = state.get("key_data")
            self._key = (None if kd is None
                         else jax.random.wrap_key_data(jnp.asarray(kd)))


_default_stream = _RngStream(int(os.environ.get("BIGDL_TPU_SEED", "0")))


def get_default_rng() -> _RngStream:
    return _default_stream


def set_seed(seed: int) -> None:
    """Global deterministic seed (BigDL: RandomGenerator.RNG.setSeed)."""
    _default_stream.reset(seed)


def next_rng_key():
    return _default_stream.next_key()
