"""Build hook: compile the native host-runtime library into the wheel.

Reference role: make-dist.sh + the BigDL-core per-OS Maven artifacts that
ship libjmkl.so inside jars (SURVEY.md §2.1).  Here `csrc/` builds to
`bigdl_tpu/lib/libbigdl_tpu_native.so`, which utils/native.py loads with a
source-tree and pure-Python fallback — so a wheel built on a machine
without a toolchain still works (host paths run the Python fallbacks).
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


ROOT = os.path.dirname(os.path.abspath(__file__))


def _build_native() -> str | None:
    csrc = os.path.join(ROOT, "csrc")
    if not os.path.isdir(csrc) or shutil.which("make") is None:
        return None
    try:
        subprocess.run(["make", "-C", csrc], check=True,
                       capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        print(f"[setup] native build failed (wheel will use Python "
              f"fallbacks): {e.stderr[-500:]}")
        return None
    so = os.path.join(csrc, "build", "libbigdl_tpu_native.so")
    return so if os.path.exists(so) else None


class BuildPyWithNative(build_py):
    def run(self):
        so = _build_native()
        if so:
            dest_dir = os.path.join(ROOT, "bigdl_tpu", "lib")
            os.makedirs(dest_dir, exist_ok=True)
            shutil.copy2(so, dest_dir)
            print(f"[setup] bundled native library: {so}")
        super().run()


setup(cmdclass={"build_py": BuildPyWithNative})
