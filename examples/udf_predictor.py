"""Serving a trained text classifier as a DataFrame-filter UDF.

Reference: `example/udfpredictor/DataframePredictor.scala` — register a
trained model as a SQL UDF and filter rows by predicted class
(`SELECT ... WHERE textClassifier(text) = k`), with `Utils.scala` doing the
text -> embedded-tensor preprocessing (GloVe-style embeddings outside the
model).  Here the query engine is pandas and the UDF is a vectorized
callable (`bigdl_tpu.serving.TextClassifierUDF`).
Run: python examples/udf_predictor.py
"""

from __future__ import annotations

import argparse

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401

SPORTS = ["match", "goal", "team", "score", "league", "coach", "win"]
TECH = ["chip", "software", "compiler", "kernel", "gpu", "cloud", "api"]


def synthetic_corpus(n, seed=0):
    r = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        label = int(r.integers(0, 2))
        vocab = SPORTS if label == 0 else TECH
        texts.append(" ".join(r.choice(vocab, size=8)))
        labels.append(label)
    return texts, np.asarray(labels)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    args = ap.parse_args(argv)

    import pandas as pd

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.text import Dictionary
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.serving import TextClassifierUDF

    Engine.init()
    texts, labels = synthetic_corpus(args.n)
    tokens = [t.split() for t in texts]
    vocab = Dictionary(tokens, vocab_size=64)
    seq_len, embed = 8, 16
    # fixed random embedding table (the reference example's GloVe role);
    # last row = padding
    r = np.random.default_rng(7)
    table = r.normal(0, 0.3, size=(vocab.vocab_size() + 2, embed)) \
        .astype(np.float32)
    table[-1] = 0.0

    model = nn.Sequential(
        nn.TemporalConvolution(embed, 32, 3), nn.ReLU(),
        nn.Max(dim=1), nn.Linear(32, 2), nn.LogSoftMax())

    udf = TextClassifierUDF(model, dictionary=vocab, embeddings=table,
                            seq_len=seq_len,
                            tokenizer=lambda s: s.split())

    def embed_text(t):
        return udf.embed(t)  # same preprocessing for training and serving

    samples = [Sample(embed_text(t), np.int32(l))
               for t, l in zip(texts, labels)]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(64,
                                                            drop_last=True))
    Optimizer(model, ds, nn.ClassNLLCriterion()) \
        .set_optim_method(Adam(5e-3)) \
        .set_end_when(Trigger.max_epoch(15)).optimize()

    df = pd.DataFrame({"text": texts, "label": labels})
    df["pred"] = udf(df["text"])
    tech_rows = df[df["pred"] == 1]  # the WHERE-clause filter
    acc = float((df["pred"] == df["label"]).mean())
    print(f"UDF accuracy={acc:.3f}; tech rows={len(tech_rows)}/{len(df)}")
    return acc


if __name__ == "__main__":
    main()
