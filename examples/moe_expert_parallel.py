"""Mixture-of-experts training with expert parallelism.

Net-new vs the reference (SURVEY.md §2.5 lists EP as absent): a Switch-
Transformer-style LM (`TransformerLM(num_experts=E, expert_axis="expert")`)
trained through the standard Optimizer on a {"data", "expert"} mesh — GSPMD
shards the expert FFN matmuls along the expert axis from the module's
sharding hints (parallel/expert.MoEFFN), and the explicit
`expert_parallel_ffn` shard_map path cross-checks the routed math.
Run: python examples/moe_expert_parallel.py [--epochs N]
"""

from __future__ import annotations

import argparse

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--num-experts", type=int, default=4)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu import Engine
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.parallel import MoEFFN, expert_parallel_ffn

    n = len(jax.devices())
    # expert axis = largest divisor of the device count (mesh must cover
    # every device: data * expert == n)
    ep = max(d for d in range(1, min(args.num_experts, n) + 1)
             if n % d == 0)
    Engine.init(mesh_shape={"data": n // ep, "expert": ep})
    set_seed(3)

    vocab, t = 12, 8
    seqs = [[(s + i) % vocab for i in range(t + 1)]
            for s in range(vocab)] * 8
    samples = [Sample(np.asarray(s[:-1], np.int32),
                      np.asarray(s[1:], np.int32)) for s in seqs]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2,
                          num_experts=args.num_experts,
                          expert_axis="expert")
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    opt = (Optimizer(model, ds, crit)
           .set_optim_method(Adam(3e-3))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    trained = opt.optimize()
    loss = opt.optim_method.hyper["loss"]

    # cross-check: the shard_map all_to_all EP path computes the same MoE
    # math as the dense/GSPMD module (on an expert-only mesh)
    from jax.sharding import Mesh
    moe = MoEFFN(16, 32, 2 * ep, capacity_factor=8.0) \
        .build(jax.random.key(0)).evaluate()
    x = jax.random.normal(jax.random.key(1), (8 * ep, 16))
    mesh = Mesh(np.array(jax.devices()[:ep]), ("expert",))
    err = float(jnp.max(jnp.abs(
        expert_parallel_ffn(mesh, moe.params, x, capacity_factor=8.0)
        - moe.forward(x))))
    print(f"MoE LM loss after {args.epochs} epochs: {loss:.4f}; "
          f"shard_map-vs-dense max|diff| = {err:.2e} over {ep} devices")
    return loss, err


if __name__ == "__main__":
    main()
