"""Put the repo root on sys.path so examples run from any cwd without an
installed wheel (the reference's examples likewise run from the source tree
via spark-submit --jars)."""
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
