"""Two-phase transfer learning: pretrain, save weights, load them into a
fresh model, freeze the feature extractor with layer-wise gradient scales,
and fine-tune only the classifier head on a shifted task.

Reference family: `example/loadmodel/` (load a pretrained model, reuse it)
plus the scaleW/scaleB layer-wise LR machinery (AbstractModule.scala:73,
DistriOptimizer.scala:729 isLayerwiseScaled).  The freeze idiom is
`set_scale_w(0)` — gradients (weight decay included) are zeroed inside the
compiled train step, and changing scales between optimize() calls
recompiles it.

Run: python examples/fine_tuning.py [--pretrain-epochs 3] [--tune-epochs 3]
"""

from __future__ import annotations

import argparse
import tempfile

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def blocks_task(n: int, seed: int, permute=None):
    """Class k lights the k-th 2x2 block; `permute` relabels classes —
    same features, shifted labels: the classic fine-tune setting."""
    r = np.random.default_rng(seed)
    xs = r.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, 10, size=n)
    for i, label in enumerate(ys):
        row, col = divmod(int(label), 5)
        xs[i, 4 + row * 10: 12 + row * 10, 2 + col * 5: 7 + col * 5, 0] += 1.5
    if permute is not None:
        ys = permute[ys]
    return xs, ys


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--pretrain-epochs", type=int, default=3)
    ap.add_argument("--tune-epochs", type=int, default=5)
    ap.add_argument("--weights", default=None,
                    help="weights file between the phases "
                         "(default: a temp file)")
    args = ap.parse_args(argv)

    import jax
    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import (Adam, Evaluator, Optimizer, Top1Accuracy,
                                 Trigger)

    Engine.init()
    tmp_dir = None
    if args.weights is None:
        tmp_dir = tempfile.TemporaryDirectory()
        weights_path = tmp_dir.name + "/pretrained.bin"
    else:
        weights_path = args.weights

    # ---- phase 1: pretrain on the source task, save weights only --------
    xs, ys = blocks_task(768, seed=0)
    src = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]
    model = LeNet5(10)
    (Optimizer(model, src, nn.ClassNLLCriterion(), batch_size=128)
     .set_optim_method(Adam(2e-3))
     .set_end_when(Trigger.max_epoch(args.pretrain_epochs))
     .optimize())
    model.save_weights(weights_path)
    print(f"phase 1: pretrained on source task -> {weights_path}")

    # ---- phase 2: fresh model, load weights, freeze features, tune head -
    tuned = LeNet5(10).build(jax.random.key(7))
    tuned.load_weights(weights_path)
    for layer in tuned.modules[:-2]:        # everything but the head
        layer.set_scale_w(0.0).set_scale_b(0.0)

    permute = np.random.default_rng(1).permutation(10)
    xt, yt = blocks_task(512, seed=2, permute=permute)
    tgt = [Sample(x, np.int32(y)) for x, y in zip(xt, yt)]
    # every frozen layer's params (all but the fc_2 head + LogSoftMax)
    feat_before = [np.asarray(a).copy()
                   for a in jax.tree.leaves(tuned.params[:-2])]
    # head-only training takes a hotter LR and smaller batches (more
    # steps): the source-task head starts at ZERO accuracy on a permuted
    # label set (no fixed points), so it must fully re-learn the mapping
    (Optimizer(tuned, tgt, nn.ClassNLLCriterion(), batch_size=64)
     .set_optim_method(Adam(1e-2))
     .set_end_when(Trigger.max_epoch(args.tune_epochs))
     .optimize())
    feat_after = [np.asarray(a)
                  for a in jax.tree.leaves(tuned.params[:-2])]
    frozen = all((a == b).all() for a, b in zip(feat_before, feat_after))

    vx, vy = blocks_task(256, seed=3, permute=permute)
    val = [Sample(x, np.int32(y)) for x, y in zip(vx, vy)]
    (_, res), = Evaluator(tuned).test(val, [Top1Accuracy()])
    acc, n = res.result()
    print(f"phase 2: frozen features untouched: {frozen}; "
          f"target-task top1 {acc:.3f} over {n}")
    if tmp_dir is not None:
        tmp_dir.cleanup()
    return acc, frozen


if __name__ == "__main__":
    main()
