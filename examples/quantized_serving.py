"""int8 serving: quantize a trained model and decode with the KV cache.

Net-new vs the reference (no quantization in BigDL v0.3): train the small
TransformerLM on a cyclic copy task, `bigdl_tpu.quantize` it to int8
weights (per-output-channel scales), and serve with every decode path —
full re-forward, KV-cache incremental, and beam search — checking the int8
model still emits the learned cycle.
Run: python examples/quantized_serving.py [--epochs N]
"""

from __future__ import annotations

import argparse

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=15)
    args = p.parse_args(argv)

    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu import Engine, quantize
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import (TransformerLM, beam_generate,
                                  cached_generate)
    from bigdl_tpu.models.transformer_lm import greedy_generate
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    Engine.init()
    set_seed(2)
    vocab, t = 12, 8
    seqs = [[(s + i) % vocab for i in range(t + 1)]
            for s in range(vocab)] * 8
    samples = [Sample(np.asarray(s[:-1], np.int32),
                      np.asarray(s[1:], np.int32)) for s in seqs]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(24, drop_last=True))
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    (Optimizer(model, ds, crit)
     .set_optim_method(Adam(3e-3))
     .set_end_when(Trigger.max_epoch(args.epochs))
     .optimize())

    q = quantize(model)
    int8_leaves = sum(l.dtype.name == "int8"
                      for l in jax.tree.leaves(q.params))
    prompt = [3, 4, 5]
    full = list(greedy_generate(q, prompt, 4, t))
    kv = list(cached_generate(q, prompt, 4, t))
    beam = list(beam_generate(q, prompt, 4, t, beam_size=3))
    assert full == kv, (full, kv)
    print(f"int8 leaves: {int8_leaves}; greedy/kv decode {full} "
          f"(identical), beam3 {beam}")
    return full, beam


if __name__ == "__main__":
    main()
