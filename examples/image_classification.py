"""Image-classification inference: save a trained model, reload it, and run
mesh-sharded batch prediction + top-1 validation over images.

Reference: `example/imageclassification/` (Predictor over rows) and
`example/loadmodel/ModelValidator.scala` (load a snapshot, evaluate top-1/5).
Run: python examples/image_classification.py
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args(argv)

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import (Adam, Optimizer, Predictor, Top1Accuracy,
                                 Top5Accuracy, Trigger)
    from examples.lenet_local import synthetic_mnist

    Engine.init()
    from bigdl_tpu.common import set_seed
    set_seed(42)  # reference RandomGenerator.setSeed role: reproducible init
    xs, ys = synthetic_mnist(args.n)

    def to_ds(x, y):
        return DataSet.array(
            [Sample(f, np.int32(l)) for f, l in zip(x, y)]).transform(
            SampleToMiniBatch(args.batch_size, drop_last=True))

    # train briefly, snapshot to the native format, reload (loadmodel flow)
    model = LeNet5(10)
    Optimizer(model, to_ds(xs, ys), nn.ClassNLLCriterion()) \
        .set_optim_method(Adam(1e-3)) \
        .set_end_when(Trigger.max_epoch(args.epochs)).optimize()
    path = os.path.join(tempfile.mkdtemp(prefix="imgcls_"), "model.bin")
    model.save(path)
    reloaded = nn.Module.load(path)

    # Predictor = mesh-sharded bulk inference (Predictor.scala:34 role)
    preds = Predictor(reloaded, batch_size=args.batch_size).predict_class(
        [Sample(f, np.int32(0)) for f in xs])
    acc = float((np.asarray(preds)[: len(ys)] == ys).mean())

    # ModelValidator-style metric evaluation
    res = reloaded.evaluate(to_ds(xs, ys), [Top1Accuracy(), Top5Accuracy()])
    print(f"predict_class acc={acc:.3f}; evaluate: {res}")
    return acc, res


if __name__ == "__main__":
    main()
