"""Local LeNet-5 training end to end: transformer chain, validation trigger,
checkpointing, resume.

Reference: `example/lenetLocal/Train.scala` + `models/lenet/Train.scala:35`
(scopt CLI, GreyImg transformer chain, everyEpoch validation + checkpoint).
Run: python examples/lenet_local.py [--epochs 2] [--checkpoint DIR]
"""

from __future__ import annotations

import argparse
import tempfile

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def synthetic_mnist(n: int, seed: int = 0):
    """Separable synthetic digits: class k lights up the k-th block."""
    r = np.random.default_rng(seed)
    xs = r.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, 10, size=n)
    for i, label in enumerate(ys):
        row, col = divmod(int(label), 5)
        xs[i, 4 + row * 10: 12 + row * 10, 2 + col * 5: 7 + col * 5, 0] += 1.5
    return xs, ys


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--checkpoint", default=None)
    args = ap.parse_args(argv)

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.image import (GreyImgNormalizer, ImgToSample,
                                         LabeledImage)
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import (Adam, Optimizer, Top1Accuracy, Trigger)

    Engine.init()
    from bigdl_tpu.common import set_seed
    set_seed(42)  # reference RandomGenerator.setSeed role: reproducible init
    xs, ys = synthetic_mnist(args.n)
    xv, yv = synthetic_mnist(args.n // 4, seed=1)
    mean, std = float(xs.mean()), float(xs.std())
    def to_ds(x, y, train=True):
        imgs = [LabeledImage(f, float(l)) for f, l in zip(x, y)]
        # `>>` = the reference Transformer's `->` chaining
        # (GreyImg pipeline: normalize -> to-sample -> batch); eval pads the
        # trailing partial batch instead of dropping it
        batcher = SampleToMiniBatch(args.batch_size, drop_last=train,
                                    pad_last=not train)
        chain = GreyImgNormalizer(mean, std) >> ImgToSample() >> batcher
        return DataSet.array(imgs).transform(chain)
    ckpt = args.checkpoint or tempfile.mkdtemp(prefix="lenet_ckpt_")

    model = LeNet5(10)
    opt = (Optimizer(model, to_ds(xs, ys), nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_epoch(args.epochs))
           .set_validation(Trigger.every_epoch(), to_ds(xv, yv, train=False),
                           [Top1Accuracy()])
           .set_checkpoint(ckpt, Trigger.every_epoch()))
    trained = opt.optimize()

    res = trained.evaluate(to_ds(xv, yv, train=False), [Top1Accuracy()])
    print(f"held-out: {res}")
    return res


if __name__ == "__main__":
    main()
