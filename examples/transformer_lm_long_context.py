"""Long-context transformer LM: train a causal LM, then run the SAME
weights with ring-attention sequence parallelism over a 'seq' mesh and
check the outputs agree — the workflow for sequences too long for one
device's memory.

Net-new vs the reference (its only sequence model is the SimpleRNN char-LM);
this is the SURVEY.md §7 long-context capability end to end.
Run: python examples/transformer_lm_long_context.py
"""

from __future__ import annotations

import argparse

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=12)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    Engine.init()
    vocab, t = args.vocab, args.seq_len
    r = np.random.default_rng(0)
    seqs = [[(int(s) + i) % vocab for i in range(t + 1)]
            for s in r.integers(0, vocab, size=192)]
    samples = [Sample(np.asarray(s[:-1], np.int32),
                      np.asarray(s[1:], np.int32)) for s in seqs]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))

    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    trained = (Optimizer(model, ds, crit)
               .set_optim_method(Adam(3e-3))
               .set_end_when(Trigger.max_epoch(args.epochs))
               .optimize())

    tok = jnp.asarray([s[:-1] for s in seqs[:4]], jnp.int32)
    dense, _ = trained.apply(trained.params, trained.state, tok,
                             training=False, rng=None)

    # same weights, ring-attention over a 'seq' mesh: sequences sharded
    # across devices never gather — the long-context execution mode
    n_ring = next(n for n in range(jax.device_count(), 0, -1) if t % n == 0)
    ring_model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                               num_heads=4, num_layers=2, seq_parallel=True)
    ring_model.build(jax.random.key(0))
    # host copy: the trained params are committed to the TRAINING mesh and
    # would conflict with the (possibly smaller) ring mesh
    ring_model.params = jax.device_get(trained.params)
    mesh = Mesh(np.array(jax.devices()[:n_ring]), ("seq",))
    with mesh:
        ring, _ = ring_model.apply(ring_model.params, ring_model.state, tok,
                                   training=False, rng=None)
    err = float(np.abs(np.asarray(dense) - np.asarray(ring)).max())
    acc = float((np.argmax(np.asarray(dense), -1) ==
                 np.asarray([s[1:] for s in seqs[:4]])).mean())

    # serving-style decoding with the public utilities: full re-forward
    # generate and the KV-cache incremental decoder must agree (greedy)
    from bigdl_tpu.models.decode import cached_generate
    from bigdl_tpu.models.transformer_lm import greedy_generate
    seed = seqs[0][:3]
    gen = greedy_generate(trained, seed, num_tokens=5, max_len=t)
    gen_kv = cached_generate(trained, seed, num_tokens=5, max_len=t)
    assert (np.asarray(gen) == np.asarray(gen_kv)).all(), (gen, gen_kv)
    print(f"next-token acc={acc:.3f}; ring-vs-dense max|diff|={err:.2e} "
          f"over {n_ring} devices; generate({seed}) -> {gen.tolist()} "
          f"(kv-cache decode identical)")
    return acc, err


if __name__ == "__main__":
    main()
