"""TensorFlow interop round-trip: export a trained model as a frozen
GraphDef, reload it, and check numeric parity.

Reference: `example/tensorflow/{Load,Save}.scala` + `utils/tf/` loaders and
savers (TensorflowLoader.scala:50, TensorflowSaver).
Run: python examples/tensorflow_interop.py
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    ap = argparse.ArgumentParser()
    args = ap.parse_args(argv)

    import jax

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.interop import load_tf, save_tf

    Engine.init()
    model = nn.Sequential(
        nn.SpatialConvolution(1, 8, 3, 3), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape((8 * 13 * 13,)), nn.Linear(8 * 13 * 13, 10),
        nn.LogSoftMax()).build(jax.random.key(0))

    x = np.random.default_rng(0).normal(size=(4, 28, 28, 1)) \
        .astype(np.float32)
    ref_out, _ = model.apply(model.params, model.state, x, training=False)

    path = os.path.join(tempfile.mkdtemp(prefix="tfio_"), "model.pb")
    save_tf(model, model.params, path, state=model.state)
    reloaded, rparams = load_tf(path)
    out, _ = reloaded.apply(rparams, reloaded.state, x, training=False)
    err = float(np.abs(np.asarray(out) - np.asarray(ref_out)).max())
    print(f"GraphDef round-trip max|diff|={err:.2e}")
    assert err < 1e-4
    return err


if __name__ == "__main__":
    main()
