"""Migration path from the reference stack: load a model saved in BigDL's
native JVM format, stream a Hadoop SequenceFile corpus prepared for the
reference, fine-tune, evaluate, and save back in the same wire format.

This is the "switch from the reference and find everything you need" story
in one script: model files (`Module.save` object streams — interop/bigdl),
datasets (`ImageNetSeqFileGenerator` `.seq` shards — dataset/seqfile), and
training/evaluation all run without the JVM or re-ETL.

Reference: `example/loadmodel/ModelValidator.scala` ("bigdl" format branch)
+ `dataset/DataSet.scala:524` SeqFileFolder.
Run: python examples/migrate_from_bigdl.py
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def _fake_reference_artifacts(root: str, classes: int = 4):
    """Stand in for artifacts the reference stack would have produced:
    a .bigdl model file and .seq dataset shards (this image has no JVM,
    so both are written through the same wire-format codecs the loaders
    parse — byte-compatible framing either way)."""
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.seqfile import write_seq_file
    from bigdl_tpu.interop import bigdl as bigdl_fmt

    model = nn.Sequential()
    model.add(nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1))
    model.add(nn.SpatialBatchNormalization(8))
    model.add(nn.ReLU())
    model.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    model.add(nn.Reshape([4 * 4 * 8]))
    model.add(nn.Linear(4 * 4 * 8, classes))
    model.add(nn.LogSoftMax())
    model.build(jax.random.PRNGKey(0))
    model_path = os.path.join(root, "pretrained.bigdl")
    bigdl_fmt.save(model, model_path)

    r = np.random.default_rng(5)
    for shard in range(2):
        recs = []
        for _ in range(64):
            label = int(r.integers(1, classes + 1))  # reference: 1-based
            img = r.integers(0, 40, size=(8, 8, 3), dtype=np.uint8)
            img[:, (label - 1) * 2:(label - 1) * 2 + 2, :] += 180
            recs.append((label, img))
        write_seq_file(os.path.join(root, f"train_{shard}.seq"), recs)
    return model_path


def _rnn_variant(root: str):
    """The sequence-model migration path (round-4 verdict #4): a
    SimpleRNN-shaped model (models/rnn/SimpleRNN.scala:29-31) written in
    the reference wire format loads, fine-tunes on a tiny char-sequence
    task, and re-exports."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.interop import bigdl as bigdl_fmt
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    I, H, O, T = 8, 16, 8, 10
    src = nn.Sequential()
    src.add(nn.Recurrent(nn.RnnCell(I, H, jnp.tanh)))
    src.add(nn.TimeDistributed(nn.Linear(H, O)))
    src.build(jax.random.PRNGKey(2))
    path = os.path.join(root, "simple_rnn.bigdl")
    bigdl_fmt.save(src, path)

    model = bigdl_fmt.load(path)
    print(f"loaded {path} (Recurrent(RnnCell) + TimeDistributed(Linear))")

    # predict-the-next-one-hot toy corpus
    r = np.random.default_rng(11)
    seqs = r.integers(0, O, size=(128, T + 1))
    xs = np.eye(I, dtype=np.float32)[seqs[:, :-1] % I]
    ys = (seqs[:, 1:] % O).astype(np.int32)
    ds = (DataSet.array([Sample(x, y) for x, y in zip(xs, ys)])
          .transform(SampleToMiniBatch(32, drop_last=True)))
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
    tuned = (Optimizer(model, ds, crit)
             .set_optim_method(SGD(learning_rate=0.1))
             .set_end_when(Trigger.max_epoch(2))
             .optimize())
    out = os.path.join(root, "simple_rnn_finetuned.bigdl")
    bigdl_fmt.save(tuned, out)
    print(f"re-exported {out} ({os.path.getsize(out)} bytes)")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args(argv)

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.image import ImgNormalizer, ImgToSample
    from bigdl_tpu.interop import bigdl as bigdl_fmt
    from bigdl_tpu.optim import Adam, Evaluator, Optimizer, Top1Accuracy, \
        Trigger

    Engine.init()
    set_seed(7)
    tmp = tempfile.TemporaryDirectory(prefix="bigdl_migrate_")
    root = tmp.name
    model_path = _fake_reference_artifacts(root)

    # 1. the reference's model file loads directly
    model = bigdl_fmt.load(model_path)
    print(f"loaded {model_path} ({len(model.modules)} layers)")

    # 2. the reference's dataset shards stream directly (out-of-core);
    # its labels are 1-based, which criterion and metric accept natively
    ds = (DataSet.seq_file_folder(root)
          .transform(ImgNormalizer((127.5,) * 3, (127.5,) * 3))
          .transform(ImgToSample())
          .transform(SampleToMiniBatch(args.batch_size, drop_last=True)))

    # 3. fine-tune + evaluate like any native model
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion(one_based=True))
           .set_optim_method(Adam(5e-3))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    trained = opt.optimize()
    res = Evaluator(trained).test(ds, [Top1Accuracy(one_based=True)])
    acc, _n = res[0][1].result()
    print(f"fine-tuned top-1 on the .seq corpus: {res[0][1]}")

    # 4. save back in the reference wire format
    out = os.path.join(root, "finetuned.bigdl")
    bigdl_fmt.save(trained, out)
    print(f"re-exported {out} ({os.path.getsize(out)} bytes, "
          "loadable on either side)")

    # 5. same story for the sequence zoo (RNN/text models)
    _rnn_variant(root)
    tmp.cleanup()
    return float(acc)


if __name__ == "__main__":
    acc = main()
    assert acc > 0.9, acc
