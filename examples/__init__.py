"""Runnable end-to-end examples mirroring the reference's `example/*` tree.

Each script is self-contained (synthetic data, seconds-scale on CPU), has a
`main(argv)` entry the test suite drives, and cites the reference example it
re-creates.  Run from anywhere: each bootstraps the repo root onto sys.path.
"""
