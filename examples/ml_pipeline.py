"""ML-pipeline example: DLClassifier on a pandas DataFrame with validation
and early stopping.

Reference: `example/MLPipeline/DLClassifierLeNet.scala` +
`org/apache/spark/ml/DLEstimator.scala:53` (fit a DataFrame with feature and
label columns, transform appends a prediction column).
Run: python examples/ml_pipeline.py
"""

from __future__ import annotations

import argparse

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=512)
    args = ap.parse_args(argv)

    import pandas as pd

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.ml import DLClassifier

    Engine.init()
    # two noisy Gaussian blobs -> flat feature arrays in a DataFrame column
    r = np.random.default_rng(0)
    label = r.integers(0, 2, size=args.n)
    centers = np.asarray([[-1.5, -1.0], [1.5, 1.0]], np.float32)
    x = (centers[label] + r.normal(0, 0.4, size=(args.n, 2))) \
        .astype(np.float32)
    df = pd.DataFrame({"features": list(x), "label": label.astype(np.float64)})
    train, val = df.iloc[: args.n * 3 // 4], df.iloc[args.n * 3 // 4:]

    model = nn.Sequential(nn.Linear(2, 32), nn.ReLU(), nn.Linear(32, 2),
                          nn.LogSoftMax())
    clf = DLClassifier(model, nn.ClassNLLCriterion(), feature_size=(2,),
                       batch_size=64, max_epoch=40,
                       features_col="features", label_col="label")
    clf.set_validation(val, None, early_stopping_patience=5)
    fitted = clf.fit(train)

    out = fitted.transform(val)
    acc = float((out["prediction"] == out["label"]).mean())
    print(f"val accuracy={acc:.3f} over {len(out)} rows")
    return acc


if __name__ == "__main__":
    main()
