"""Text-classification CNN: tokenizer -> Dictionary -> embedded sequences ->
TemporalConvolution classifier (news20 shape, synthetic corpus).

Reference: `example/textclassification/TextClassifier.scala` (+ helpers in
`example/utils/`): GloVe embeddings + TemporalConvolution + max-over-time.
Run: python examples/text_classification.py
"""

from __future__ import annotations

import argparse

import numpy as np

if __package__ in (None, ""):  # run as a script from any cwd
    import _bootstrap  # noqa: F401
else:
    from . import _bootstrap  # noqa: F401


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=384)
    ap.add_argument("--classes", type=int, default=3)
    args = ap.parse_args(argv)

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer
    from bigdl_tpu.optim import Adam, Optimizer, Top1Accuracy, Trigger

    Engine.init()
    # synthetic corpus: class k draws from its own keyword pool
    r = np.random.default_rng(0)
    pools = [[f"w{k}_{i}" for i in range(12)] for k in range(args.classes)]
    texts = []
    labels = r.integers(0, args.classes, size=args.n)
    for lbl in labels:
        texts.append(" ".join(r.choice(pools[int(lbl)], size=10)))

    tok = SentenceTokenizer()
    tokens = [list(tok([t]))[0] for t in texts]
    vocab = Dictionary(tokens, vocab_size=100)
    seq_len, embed = 10, 20
    table = r.normal(0, 0.3, size=(vocab.vocab_size() + 2, embed)) \
        .astype(np.float32)
    table[-1] = 0.0
    pad = len(table) - 1

    def encode(toks):
        idx = np.full((seq_len,), pad, np.int64)
        for i, t in enumerate(toks[:seq_len]):
            idx[i] = vocab.get_index(t)
        return table[idx]

    samples = [Sample(encode(t), np.int32(l))
               for t, l in zip(tokens, labels)]
    split = args.n * 3 // 4
    to_ds = lambda ss: DataSet.array(ss).transform(
        SampleToMiniBatch(64, drop_last=True))

    model = nn.Sequential(
        nn.TemporalConvolution(embed, 48, 3), nn.ReLU(),
        nn.Max(dim=1), nn.Linear(48, args.classes), nn.LogSoftMax())
    Optimizer(model, to_ds(samples[:split]), nn.ClassNLLCriterion()) \
        .set_optim_method(Adam(5e-3)) \
        .set_end_when(Trigger.max_epoch(15)).optimize()

    res = model.evaluate(to_ds(samples[split:]), [Top1Accuracy()])
    print(f"held-out: {res}")
    return res


if __name__ == "__main__":
    main()
