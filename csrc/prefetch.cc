// Multithreaded BDRecord shard prefetcher: N reader threads pull whole
// shards off a work queue and push records into one bounded ring buffer
// the consumer pops from.  This is the native concurrent-read path playing
// the role Spark partitions play for the reference's SequenceFile datasets
// (dataset/DataSet.scala:319 SeqFileFolder: one task per partition reads
// its shard in parallel); the Python MT batcher then overlaps transform
// work on top.  Plain C++17: std::thread + mutex/condvar, no deps.
//
// C ABI (ctypes-friendly, mirrors bigdl_record_reader_*):
//   bigdl_prefetch_open(paths, n_paths, n_threads, capacity) -> handle
//   bigdl_prefetch_next(handle) -> record length (>=0), -1 end, -2 error
//   bigdl_prefetch_data(handle) -> pointer to last record's bytes
//   bigdl_prefetch_close(handle)
// Record order is nondeterministic across shards (like Spark partition
// interleaving); order within one shard is preserved per thread.
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "crc32c.h"

extern "C" {
// from recordio.cc (declarations must match its definitions exactly)
void* bigdl_record_reader_open(const char* path);
int64_t bigdl_record_reader_next(void* handle);
const char* bigdl_record_reader_data(void* handle);
void bigdl_record_reader_close(void* handle);
}

namespace {

struct Prefetcher {
  std::vector<std::string> paths;
  size_t next_path = 0;           // guarded by mu
  std::deque<std::vector<char>> ring;
  size_t capacity;
  bool error = false;
  int live_workers = 0;
  std::mutex mu;
  std::condition_variable not_empty;   // consumer waits
  std::condition_variable not_full;    // producers wait
  std::vector<std::thread> threads;
  std::vector<char> current;      // last record handed to the consumer
  bool closing = false;

  void Worker() {
    for (;;) {
      std::string path;
      {
        std::lock_guard<std::mutex> lk(mu);
        if (closing || next_path >= paths.size()) break;
        path = paths[next_path++];
      }
      void* r = bigdl_record_reader_open(path.c_str());
      if (!r) {
        std::lock_guard<std::mutex> lk(mu);
        error = true;
        break;
      }
      for (;;) {
        int64_t n = bigdl_record_reader_next(r);
        if (n < 0) {
          if (n < -1) {  // corrupt record (bad CRC / truncated)
            std::lock_guard<std::mutex> lk(mu);
            error = true;
          }
          break;
        }
        std::vector<char> rec(static_cast<size_t>(n));
        if (n > 0) memcpy(rec.data(), bigdl_record_reader_data(r),
                          static_cast<size_t>(n));
        std::unique_lock<std::mutex> lk(mu);
        not_full.wait(lk, [&] { return ring.size() < capacity || closing; });
        if (closing) break;
        ring.push_back(std::move(rec));
        not_empty.notify_one();
      }
      bigdl_record_reader_close(r);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (closing || error) break;
      }
    }
    std::lock_guard<std::mutex> lk(mu);
    --live_workers;
    not_empty.notify_all();  // consumer may be waiting on the last worker
  }
};

}  // namespace

extern "C" {

void* bigdl_prefetch_open(const char** paths, int64_t n_paths,
                          int64_t n_threads, int64_t capacity) {
  if (n_paths <= 0 || n_threads <= 0 || capacity <= 0) return nullptr;
  auto* p = new Prefetcher();
  p->paths.assign(paths, paths + n_paths);
  p->capacity = static_cast<size_t>(capacity);
  int workers = static_cast<int>(
      n_threads < n_paths ? n_threads : n_paths);
  p->live_workers = workers;
  for (int i = 0; i < workers; ++i)
    p->threads.emplace_back(&Prefetcher::Worker, p);
  return p;
}

// >=0: record of that many bytes available via bigdl_prefetch_data.
// -1: clean end of all shards.  -2: IO/CRC error (after draining).
int64_t bigdl_prefetch_next(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [&] {
    return !p->ring.empty() || p->live_workers == 0;
  });
  if (p->ring.empty()) return p->error ? -2 : -1;
  p->current = std::move(p->ring.front());
  p->ring.pop_front();
  p->not_full.notify_one();
  return static_cast<int64_t>(p->current.size());
}

void* bigdl_prefetch_data(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  return p->current.data();
}

void bigdl_prefetch_close(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->closing = true;
    p->not_full.notify_all();
    p->not_empty.notify_all();
  }
  for (auto& t : p->threads) t.join();
  delete p;
}

}  // extern "C"
