#include "crc32c.h"

#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <nmmintrin.h>
#define BIGDL_HAVE_SSE42_INTRIN 1
#endif

namespace bigdl {
namespace {

// Sliced-by-8 software CRC32C. Tables generated at first use.
struct Tables {
  uint32_t t[8][256];
  Tables() {
    const uint32_t poly = 0x82f63b78u;
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
      t[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xff];
  }
};

const Tables& tables() {
  static Tables tb;
  return tb;
}

uint32_t Crc32cSoftware(const void* data, size_t len, uint32_t seed) {
  const Tables& tb = tables();
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint32_t crc = seed ^ 0xffffffffu;
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    word ^= crc;
    crc = tb.t[7][word & 0xff] ^ tb.t[6][(word >> 8) & 0xff] ^
          tb.t[5][(word >> 16) & 0xff] ^ tb.t[4][(word >> 24) & 0xff] ^
          tb.t[3][(word >> 32) & 0xff] ^ tb.t[2][(word >> 40) & 0xff] ^
          tb.t[1][(word >> 48) & 0xff] ^ tb.t[0][word >> 56];
    p += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ tb.t[0][(crc ^ *p++) & 0xff];
  return crc ^ 0xffffffffu;
}

#ifdef BIGDL_HAVE_SSE42_INTRIN
__attribute__((target("sse4.2"))) uint32_t Crc32cHardware(const void* data,
                                                          size_t len,
                                                          uint32_t seed) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint64_t crc = seed ^ 0xffffffffu;
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, p, 8);
    crc = _mm_crc32_u64(crc, word);
    p += 8;
    len -= 8;
  }
  uint32_t crc32 = static_cast<uint32_t>(crc);
  while (len--) crc32 = _mm_crc32_u8(crc32, *p++);
  return crc32 ^ 0xffffffffu;
}

bool HaveSse42() { return __builtin_cpu_supports("sse4.2"); }
#endif

}  // namespace

uint32_t Crc32cExtend(uint32_t crc, const void* data, size_t len) {
#ifdef BIGDL_HAVE_SSE42_INTRIN
  static const bool hw = HaveSse42();
  if (hw) return Crc32cHardware(data, len, crc);
#endif
  return Crc32cSoftware(data, len, crc);
}

uint32_t Crc32c(const void* data, size_t len) {
  return Crc32cExtend(0, data, len);
}

}  // namespace bigdl

extern "C" {

uint32_t bigdl_crc32c(const char* data, size_t len) {
  return bigdl::Crc32c(data, len);
}

// Streaming continuation: `crc` is the finalized CRC32C of the bytes seen
// so far (0 for the first chunk); the return value is the finalized
// CRC32C of the concatenation — the checkpoint framer
// (bigdl_tpu/utils/file_io.py) streams multi-GB pickles through this.
uint32_t bigdl_crc32c_extend(uint32_t crc, const char* data, size_t len) {
  return bigdl::Crc32cExtend(crc, data, len);
}

uint32_t bigdl_masked_crc32c(const char* data, size_t len) {
  return bigdl::MaskedCrc32c(data, len);
}

}  // extern "C"
