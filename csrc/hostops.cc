// Host-side parallel kernels for the data path and the bf16 wire format.
//
// Reference: BigDL's FP16CompressedTensor compresses float32 gradients to
// bf16-style truncated halves with a loop parallelised over
// Engine.coreNumber() threads (parameters/FP16CompressedTensor.scala:122-222,
// truncate at :271-279).  On TPU the *gradient* path is native bf16 inside
// XLA; these host kernels serve checkpoint compression and the input
// pipeline (batch assembly = the role of MTLabeledBGRImgToBatch's thread
// pool, dataset/image/MTLabeledBGRImgToBatch.scala).
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

int g_num_threads = static_cast<int>(std::thread::hardware_concurrency());

// Run fn(begin, end) over [0, n) split across nthreads.
template <typename Fn>
void ParallelFor(size_t n, int nthreads, Fn fn) {
  if (nthreads <= 1 || n < (1u << 16)) {
    fn(size_t{0}, n);
    return;
  }
  nthreads = std::min<size_t>(nthreads, n);
  std::vector<std::thread> workers;
  size_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    size_t b = t * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    workers.emplace_back([=] { fn(b, e); });
  }
  for (auto& w : workers) w.join();
}

// Round-to-nearest-even f32 -> bf16, matching XLA/TPU semantics (the
// reference truncates — FP16CompressedTensor.scala:271-279 keeps the top 16
// bits; rounding is strictly more accurate and matches the hardware).
inline uint16_t F32ToBf16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  if ((bits & 0x7fffffffu) > 0x7f800000u)  // NaN: quiet it, keep sign
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7fffu + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

}  // namespace

extern "C" {

void bigdl_set_num_threads(int n) { g_num_threads = n > 0 ? n : 1; }
int bigdl_get_num_threads() { return g_num_threads; }

void bigdl_f32_to_bf16(const float* src, uint16_t* dst, size_t n) {
  ParallelFor(n, g_num_threads, [&](size_t b, size_t e) {
    for (size_t i = b; i < e; ++i) dst[i] = F32ToBf16(src[i]);
  });
}

void bigdl_bf16_to_f32(const uint16_t* src, float* dst, size_t n) {
  ParallelFor(n, g_num_threads, [&](size_t b, size_t e) {
    for (size_t i = b; i < e; ++i) {
      uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
      std::memcpy(&dst[i], &bits, 4);
    }
  });
}

// Gather n equally-sized rows into one contiguous batch buffer (the memcpy
// half of SampleToMiniBatch / MTLabeledBGRImgToBatch batching).
void bigdl_gather_rows(char* dst, const char* const* srcs, size_t row_bytes,
                       size_t n) {
  ParallelFor(n * row_bytes, g_num_threads, [&](size_t b, size_t e) {
    size_t first = b / row_bytes, last = (e + row_bytes - 1) / row_bytes;
    for (size_t i = first; i < last && i < n; ++i) {
      size_t lo = std::max(b, i * row_bytes) - i * row_bytes;
      size_t hi = std::min(e, (i + 1) * row_bytes) - i * row_bytes;
      if (hi > lo) std::memcpy(dst + i * row_bytes + lo, srcs[i] + lo, hi - lo);
    }
  });
}

// Parallel sum of k float buffers into dst (the gradient-aggregation loop of
// DistriOptimizer.scala:226-250, kept for host-side reference optimizers).
// dst is fully overwritten (initialized from srcs[0]).
void bigdl_reduce_sum_f32(float* dst, const float* const* srcs, int k,
                          size_t n) {
  if (k <= 0) return;
  ParallelFor(n, g_num_threads, [&](size_t b, size_t e) {
    std::memcpy(dst + b, srcs[0] + b, (e - b) * sizeof(float));
    for (int j = 1; j < k; ++j) {
      const float* s = srcs[j];
      for (size_t i = b; i < e; ++i) dst[i] += s[i];
    }
  });
}

}  // extern "C"
