// BDRecord file IO: the sharded record format replacing BigDL's Hadoop
// SequenceFile datasets (reference: dataset/DataSet.scala:319 SeqFileFolder;
// ETL in models/utils/ImageNetSeqFileGenerator.scala).  TFRecord framing:
//   u64 length | u32 masked_crc(length) | payload | u32 masked_crc(payload)
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "crc32c.h"

namespace {

struct Writer {
  FILE* f;
};

struct Reader {
  FILE* f;
  std::vector<char> buf;
};

bool WriteAll(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

bool ReadAll(FILE* f, void* p, size_t n) { return fread(p, 1, n, f) == n; }

}  // namespace

extern "C" {

void* bigdl_record_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  // Large stdio buffer: sequential-write workload.
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  return new Writer{f};
}

int bigdl_record_writer_write(void* handle, const char* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  char header[8];
  std::memcpy(header, &len, 8);
  uint32_t hcrc = bigdl::MaskedCrc32c(header, 8);
  uint32_t pcrc = bigdl::MaskedCrc32c(data, len);
  if (!WriteAll(w->f, header, 8) || !WriteAll(w->f, &hcrc, 4) ||
      !WriteAll(w->f, data, len) || !WriteAll(w->f, &pcrc, 4))
    return -1;
  return 0;
}

int bigdl_record_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = fclose(w->f);
  delete w;
  return rc;
}

void* bigdl_record_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  setvbuf(f, nullptr, _IOFBF, 1 << 20);
  return new Reader{f, {}};
}

// Returns payload length (>=0), -1 on clean EOF, -2 on corruption/short read.
// Exceptions (e.g. bad_alloc on a bogus length from a truncated file) must
// not unwind through the ctypes FFI frame, so the body is fenced.
int64_t bigdl_record_reader_next(void* handle) try {
  Reader* r = static_cast<Reader*>(handle);
  char header[8];
  size_t got = fread(header, 1, 8, r->f);
  if (got == 0) return -1;
  if (got < 8) return -2;
  uint32_t hcrc;
  if (!ReadAll(r->f, &hcrc, 4)) return -2;
  if (hcrc != bigdl::MaskedCrc32c(header, 8)) return -2;
  uint64_t len;
  std::memcpy(&len, header, 8);
  r->buf.resize(len);
  if (len && !ReadAll(r->f, r->buf.data(), len)) return -2;
  uint32_t pcrc;
  if (!ReadAll(r->f, &pcrc, 4)) return -2;
  if (pcrc != bigdl::MaskedCrc32c(r->buf.data(), len)) return -2;
  return static_cast<int64_t>(len);
} catch (...) {
  return -2;
}

const char* bigdl_record_reader_data(void* handle) {
  return static_cast<Reader*>(handle)->buf.data();
}

void bigdl_record_reader_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  fclose(r->f);
  delete r;
}

}  // extern "C"
