// CRC32C (Castagnoli) — the checksum framing BigDL vendored as
// netty/Crc32c.java for TensorBoard record files (reference:
// visualization/tensorboard/RecordWriter.scala:44-57).  Here it also frames
// the BDRecord data files (bigdl_tpu/utils/recordio.py).
#ifndef BIGDL_TPU_CRC32C_H_
#define BIGDL_TPU_CRC32C_H_

#include <cstddef>
#include <cstdint>

namespace bigdl {

// One-shot CRC32C of `len` bytes. Uses SSE4.2 when the CPU supports it.
uint32_t Crc32c(const void* data, size_t len);

// Streaming continuation: finalized-CRC in, finalized-CRC out (seed 0 for
// the first chunk), so Crc32cExtend(Crc32cExtend(0, a), b) == Crc32c(a+b).
uint32_t Crc32cExtend(uint32_t crc, const void* data, size_t len);

// TFRecord-style masked CRC.
inline uint32_t MaskedCrc32c(const void* data, size_t len) {
  uint32_t crc = Crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

}  // namespace bigdl

#endif  // BIGDL_TPU_CRC32C_H_
